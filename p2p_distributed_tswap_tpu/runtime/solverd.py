"""solverd — the TPU solver daemon behind the centralized manager's
``--solver=tpu`` mode (the BASELINE.json north-star deployment shape).

The C++ centralized manager ships global agent state over bus topic "solver"
as a plan_request each planning tick; this daemon runs ONE batched TSWAP step
on the accelerator and replies with per-agent next positions (and possibly
swapped goals).  The manager stays the system of record — it converts moves
to move_instruction messages exactly as with its native solver.

Device-side design: fixed-capacity lanes (next power of two over the fleet
size) with the step kernel's ``active`` mask, so fleet growth causes at most
O(log N) recompiles; direction-field rows are cached per goal and recomputed
only for goals not seen before (LRU eviction), since TSWAP goal exchange
permutes goals far more often than the task lifecycle creates new ones.

Wire (legacy JSON, always accepted):
      plan_request  {type, seq, agents:[{peer_id, pos:[x,y], goal:[x,y]}]}
      plan_response {type, seq, duration_micros,
                     moves:[{peer_id, next_pos:[x,y], goal:[x,y]}]}
      (``goal`` in a move carries the step's swap/rotation decisions; the
      manager adopts them as TASK re-assignments — the task follows the
      exchanged goal and both Tasks are re-broadcast
      (manager_centralized adopt_goal_exchanges).  Round 4 ignored the
      returned goals, which livelocked head-on pairs: rotation, retreat,
      goal reset, repeat.)

Fast path (packed1, negotiated via the request's ``caps`` field — see
runtime/plan_codec.py): requests carry base64 packed int32 snapshots/deltas
instead of per-agent JSON.  The fleet state then lives DEVICE-RESIDENT
between ticks (pos/goal/slot/active arrays at capacity) and a delta tick
scatters in only the O(churn) changed lanes instead of re-uploading O(N);
a seq gap in the delta chain makes the daemon publish
``plan_snapshot_request`` and the manager resyncs with a full snapshot.
Responses are packed too (only lanes that moved or changed goal).  The
daemon loop is PIPELINED: the device step for request k is dispatched
without blocking, the decode of request k+1 and the encode of response k
overlap its execution, and the output fetch happens only when the response
is actually due (dispatch-then-poll; ``solverd.pipeline_overlap_ms``).

Multi-tenant mode (ISSUE 8): with ``--tenants ns0,ns1,...`` and/or
``--multi-tenant`` ONE daemon serves many namespaced fleets
(runtime/busns.py — each tenant's manager runs unmodified behind
``JG_BUS_NS``).  Every admitted tenant owns one row of a [T, L]
device-resident super-batch (pow2-padded on both axes); one jitted
vmapped step plans every tenant per request burst, the direction-field
cache is shared across tenants, and per-tenant packed-delta chains keep
the O(churn) scatter.  Admission is budgeted (``--max-tenants``,
``--tenant-lanes``): overflow evicts the least-recently-active tenant
idle past ``--tenant-idle-ms``, and re-admission snapshot-resyncs
through the existing ``plan_snapshot_request`` path (lossless — the
manager is the system of record).  Multi-tenant mode is packed-wire
only.  See TenantSlab/MultiTenantRunner below;
``analysis/tenant_scaling.py`` is the measurement harness.

Usage: python -m p2p_distributed_tswap_tpu.runtime.solverd
           [--port 7400] [--map FILE] [--capacity-min 16] [--warm N]
           [--trace] [--tenants t0,t1] [--multi-tenant]
           [--max-tenants N] [--tenant-lanes N] [--tenant-idle-ms MS]

Observability (obs/): with ``JG_TRACE=1`` (or ``--trace``) every tick is
traced phase-by-phase (decode -> cache lookup -> field sweep -> step
dispatch -> device sync -> encode) into Chrome trace-event JSONL plus a
per-tick heartbeat line judged against the manager's 500 ms planning
budget; ``kill -USR1`` or a bus ``stats_request`` message dumps a
machine-readable stats snapshot at any time (tracing not required).
Live registry counters for the fast path: ``solverd.decode_bytes``,
``solverd.delta_agents``, ``solverd.pipeline_overlap_ms``,
``solverd.seq_gaps``, ``solverd.snapshots_applied``.

``--warm N`` pre-compiles the whole planning path for an N-agent fleet
BEFORE the readiness banner: the step program at capacity(N), the
field-sweep chunk program, and N warm field rows.  A fleet started with
--warm sized to its agent count sees ZERO recompile stalls and never
trips the manager's native failover at startup (VERDICT r4 item 1: the
round-4 hardware run opened with a 77 s capacity-recompile stall).
"""

from __future__ import annotations

import argparse
import base64
import functools
import json
import os
import signal
import sys
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.obs import HeartbeatWriter, registry, trace
from p2p_distributed_tswap_tpu.obs import audit as obs_audit
from p2p_distributed_tswap_tpu.obs import events as obs_events
from p2p_distributed_tswap_tpu.obs import flightrec
from p2p_distributed_tswap_tpu.obs.beacon import MetricsBeacon
from p2p_distributed_tswap_tpu.obs.heartbeat import TICK_BUDGET_MS
from p2p_distributed_tswap_tpu.ops import field_repair
from p2p_distributed_tswap_tpu.ops import sector
from p2p_distributed_tswap_tpu.ops.distance import (
    DIR_DXDY,
    DIR_STAY,
    PACKED_STAY,
    direction_fields,
    directions_from_distance,
    distance_fields,
    pack_directions,
    packed_cells,
)
from p2p_distributed_tswap_tpu.parallel import solver_mesh
from p2p_distributed_tswap_tpu.parallel import virtual_mesh
from p2p_distributed_tswap_tpu.runtime import busns
from p2p_distributed_tswap_tpu.runtime import plan_codec as pcodec
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient
from p2p_distributed_tswap_tpu.solver.step import step_parallel

# Dynamic tenant admission rides this un-namespaced control topic
# (ISSUE 8): {"type":"tenant_hello","ns":X} subscribes + admits tenant X,
# answered with {"type":"tenant_welcome","ns":X}.  The hello must come
# from an UN-NAMESPACED client (an orchestrator/operator tool, like the
# tenant_scaling harness's watcher) — a fleet behind JG_BUS_NS prefixes
# everything it publishes and cannot reach this topic itself; whoever
# spawns tenant fleets announces them.  Static `--tenants` lists skip
# the dance entirely.
ADMIT_TOPIC = "solver.admit"


def _donation_ok() -> bool:
    """Donate resident buffers to the scatter program only where donation
    actually works: real TPU/GPU backends.  The axon tunnel raises
    INVALID_ARGUMENT on donated programs and the CPU backend ignores
    donation with a warning (see .claude/skills/verify — 'never rely on
    donate_argnums here'), so both default off.  ``JG_DONATE=1`` forces it
    on, ``JG_DONATE=0`` off."""
    env = os.environ.get("JG_DONATE", "")
    if env == "1":
        return True
    if env == "0":
        return False
    try:
        return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")
    except RuntimeError:
        return False


def _pad_pow2_chunk(min_chunk: int, *arrays):
    """Pad parallel per-lane arrays to the next power-of-two chunk >=
    ``min_chunk`` with duplicate writes of entry 0 (same values ->
    idempotent), so churn bursts retrace the scatter program O(log
    churn) times, not per distinct length.  Shared by the flat resident
    scatter and the tenant slab's row scatter — the padding invariant
    must never diverge between them."""
    m = len(arrays[0])
    chunk = min_chunk
    while chunk < m:
        chunk *= 2
    if chunk == m:
        return arrays
    pad = chunk - m
    return tuple(np.concatenate([a, np.full(pad, a[0], a.dtype)])
                 for a in arrays)


class FieldQueueEntry:
    """One queued field sweep: its cause (``fresh_goal`` — a lane is
    parked on the STAY row waiting for it; ``prime`` — a manager
    prefetch hint; ``repair`` — a world toggle invalidated the cached
    row) and the queue clock at enqueue time, for the starvation age
    bound (ISSUE 9 satellite)."""

    __slots__ = ("cause", "enq")

    def __init__(self, cause: str, enq: int):
        self.cause = cause
        self.enq = enq


def parse_world_update(data: dict) -> Optional[List[Tuple[int, bool]]]:
    """``[(cell, blocked)]`` from a ``world_update`` message — packed
    world1 block (``codec: packed1``) or the JSON ``toggles`` list;
    None on a malformed frame."""
    if data.get("codec") == pcodec.CODEC_NAME:
        try:
            pkt = pcodec.decode_b64(data.get("data") or "")
            return pcodec.decode_world(pkt)
        except pcodec.CodecError:
            return None
    raw = data.get("toggles")
    if not isinstance(raw, list):
        return None
    out = []
    for e in raw:
        try:
            out.append((int(e[0]), bool(e[1])))
        except (TypeError, ValueError, IndexError):
            return None
    return out


class PendingPlan:
    """A dispatched-but-unfetched device step (dispatch-then-poll): holds
    the device output handles plus everything fetch() needs to finish the
    plan after host work has overlapped the device execution."""

    __slots__ = ("mode", "agents", "cap", "n", "new_pos", "new_goal",
                 "base_pos", "base_goal", "base_active",
                 "t_plan0", "t_sweep0", "t_disp0", "t_disp_end")


class PlanService:
    """Batched one-step planner with goal-field caching.

    Two request paths share the step program and the field cache:

    - ``plan()`` / ``dispatch()``: stateless legacy path — the request
      carries the whole fleet (JSON wire).
    - ``resident_apply()`` + ``resident_dispatch()``: the packed fast
      path — fleet state (pos/goal/slot/active) stays on device between
      ticks and deltas scatter in O(churn) lanes.  Goals referenced by
      resident agents are pinned against LRU eviction via refcounts.
    """

    # Fresh-goal sweeps per jitted program call: new goals arrive a few per
    # tick (task churn), so a fixed small chunk keeps the program cached
    # while bounding padding waste.  The startup burst just loops chunks.
    FIELD_CHUNK = 8
    # Packed field-cache memory ceiling: rows are preallocated at FULL
    # budget up front so the step program's dirs shape never changes — the
    # round-3 stress run showed each cache-growth recompile stalling whole
    # ticks (tests/test_solverd_stress.py).
    CACHE_BYTES = 256 << 20
    # Delta scatters pad to the next power of two at least this size, so
    # churn bursts retrace the scatter program O(log churn) times, not per
    # distinct delta length.
    SCATTER_CHUNK_MIN = 8
    # Dynamic-world bookkeeping bounds (ISSUE 9): the toggle log compacts
    # past this many entries (every cached field then repairs via full
    # recompute on next touch — correct, just not incremental), and
    # queued sweeps older than FIELD_QUEUE_MAX_AGE process_field_queue
    # calls jump the whole queue so sustained fresh-goal churn (which
    # front-inserts) cannot starve repair/prime entries.
    WORLD_LOG_MAX = 4096
    FIELD_QUEUE_MAX_AGE = 8
    # Host repair-mirror budget: dist (int32) + dirs (uint8) = 5
    # bytes/cell/goal, UNPACKED (the device cache is nibble-packed
    # precisely to halve memory, so mirrors need their own ceiling).  A
    # goal whose mirror is evicted keeps its packed row; its next repair
    # just falls back to one full recompute.
    MIRROR_BYTES = 256 << 20
    # Start-cell hints retained per goal for the sector planner (ISSUE
    # 19): folding more distinct lane positions than this into one
    # corridor adds sectors without adding route information (plan_goal
    # itself folds at most sector.MAX_PLAN_STARTS per call; later lanes
    # re-enter lazily).
    SECTOR_HINTS_MAX = 64

    def __init__(self, grid: Grid, capacity_min: int = 16,
                 field_cache: int = 4096,
                 mesh: Optional["solver_mesh.SolverMesh"] = None):
        self.grid = grid
        self.free = jnp.asarray(grid.free)
        # Mesh mode (ISSUE 13): the field cache / lanes shard over a
        # device mesh and the step + sweeps run under shard_map.  mesh
        # is None on the default single-device path — every mesh branch
        # below is gated on it, so unset JG_SOLVER_MESH keeps this class
        # byte-identical to the pre-mesh daemon.
        self.mesh = mesh
        if mesh is not None:
            mesh.validate_grid(grid)
            # lane capacities must divide over the agent shards; pow2
            # doubling from a shard-multiple floor preserves the property
            capacity_min = mesh.round_lanes(capacity_min)
        self.capacity_min = capacity_min
        pc = packed_cells(grid.num_cells)
        self.max_fields = max(capacity_min,
                              min(field_cache, self.CACHE_BYTES // (4 * pc)))
        # goal cell -> row index into the dirs buffer
        self.goal_rows: "OrderedDict[int, int]" = OrderedDict()
        self.dirs: jnp.ndarray | None = None  # (rows, ceil(HW/8)) packed uint32
        if mesh is None:
            self._step = functools.partial(jax.jit,
                                           static_argnums=0)(step_parallel)
        else:
            self._step = mesh.make_step()
        # jitted fixed-chunk sweep: eager per-op dispatch of the doubling
        # scan cost ~5 s/tick on a 1-core host (stress test, round 3).
        # ``free`` is an ARGUMENT, not a closure capture: a closure would
        # bake the mask into the traced program as a constant and world
        # toggles (apply_world_update) would silently sweep the old world.
        if mesh is None:
            self._fields = jax.jit(lambda free, goals: pack_directions(
                direction_fields(free, goals).reshape(goals.shape[0], -1)))

            def _fields_dist_impl(free, goals):
                # dynamic-world variant: same sweeps, but the raw
                # distance field and unpacked codes come back too — the
                # host mirrors incremental repair starts from
                # (ops/field_repair.py)
                d = distance_fields(free, goals)
                dirs = directions_from_distance(d, free)
                return (pack_directions(dirs.reshape(goals.shape[0], -1)),
                        d, dirs)

            self._fields_dist = jax.jit(_fields_dist_impl)
        else:
            # sharded twins: goal batch over the agents axis, sweeps
            # optionally H-banded over the tiles axis — bit-identical
            self._fields = mesh.make_fields(grid)
            self._fields_dist = mesh.make_fields_dist(grid)
        # Dynamic world (ISSUE 9): obstacle cells toggle mid-run via
        # caps-negotiated world_update messages.  JG_DYNAMIC_WORLD=0 is
        # the kill switch (updates ignored, zero bookkeeping — the
        # static path is byte-identical); =1 keeps dist/dirs host
        # mirrors from process start so the FIRST toggle already repairs
        # incrementally; unset flips mirror-keeping on lazily at the
        # first accepted update (pre-existing rows then repair via one
        # full recompute each).
        env_dw = os.environ.get("JG_DYNAMIC_WORLD", "")
        self.dynamic_world = env_dw != "0"
        self.keep_dist = env_dw == "1"
        # world-epoch tracking (ISSUE 10 satellite): always-present
        # gauges so the fleet_top WORLD line can show a 0-epoch planner
        registry.get_registry().gauge("solverd.world_seq", 0)
        registry.get_registry().gauge("solverd.dynamic_world",
                                      1 if self.dynamic_world else 0)
        # injected-corruption test hook (ISSUE 10, JG_AUDIT_TEST_HOOKS):
        # lane -> (field, forced_value, view) re-imposed after every
        # state application, so the fault persists like a real bad lane
        # instead of healing on the next delta
        self.corrupt: Dict[int, Tuple[str, int, str]] = {}
        self.free_np = np.asarray(grid.free).copy()
        self.world_seq = 0
        self.world_log: List[int] = []      # toggled cells, in order
        self.dist_mirror: Dict[int, np.ndarray] = {}  # goal -> (H,W) i32
        self.dirs_mirror: Dict[int, np.ndarray] = {}  # goal -> (H,W) u8
        self.dist_seq: Dict[int, int] = {}  # goal -> log length at sweep
        self.max_mirrors = max(16, self.MIRROR_BYTES // (5 * grid.num_cells))
        # Hierarchical sector planner (ISSUE 19): with JG_SECTOR=1 a
        # fresh goal gets a corridor plan (O(route-sector area)) instead
        # of a full-grid sweep.  Unset, self.sector stays None and every
        # sector branch below is dead code — the wire and the compiled
        # programs are byte-identical (tests/test_sector.py pins this).
        # The planner holds free_np BY REFERENCE: apply_world_update's
        # in-place mask mutation is visible to it immediately, and
        # apply_toggles repairs the portal graph right after.
        self.sector: Optional["sector.SectorPlanner"] = None
        self.sector_hints: Dict[int, set] = {}  # goal -> start cells
        if sector.sector_enabled():
            self.sector = sector.SectorPlanner(self.free_np)
            registry.get_registry().gauge("solverd.sector_cells",
                                          self.sector.s)
        self.queue_clock = 0                # process_field_queue calls
        self._last_cap = 0
        self._seen_programs = 0
        # device-resident fleet state (packed fast path); host mirrors stay
        # in lockstep so responses and delta diffs never fetch the arrays
        self.r_cap = 0
        self.d_pos = self.d_goal = self.d_slot = self.d_active = None
        self.h_pos = np.zeros(0, np.int32)
        self.h_goal = np.zeros(0, np.int32)
        self.h_slot = np.zeros(0, np.int32)
        self.h_active = np.zeros(0, bool)
        self.goal_ref: Dict[int, int] = {}  # resident goal -> lane count
        self._scatter = None
        # donation composes badly with explicit output shardings (and the
        # mesh scatter re-lays-out anyway): mesh mode forces it off
        self._scatter_donate = _donation_ok() and mesh is None
        # Deferred field repair (packed fast path): a fresh goal whose
        # direction field is not cached yet does NOT stall the tick — the
        # agent plans one tick on the reserved all-STAY row (it waits in
        # place; the goal-adjacency shortcut still moves it if 1 cell
        # away) while the sweep runs in the daemon's idle window between
        # ticks (process_field_queue).  On the CPU fallback one sweep
        # program costs ~300 ms of dispatch-bound time — paying it inline
        # would eat half the 500 ms tick budget for ONE task arrival.
        # Off by default on accelerator backends (sweeps are ms there);
        # JG_DEFER_FIELDS=1/0 overrides.
        env_defer = os.environ.get("JG_DEFER_FIELDS", "")
        if env_defer in ("0", "1"):
            self.defer_fields = env_defer == "1"
        else:
            try:
                self.defer_fields = jax.default_backend() == "cpu"
            except RuntimeError:
                self.defer_fields = False
        self.field_queue: "OrderedDict[int, None]" = OrderedDict()
        self.lane_wait: Dict[int, int] = {}   # lane -> goal it awaits
        self.wait_lanes: Dict[int, set] = {}  # goal -> waiting lanes
        # observability: cumulative counters + the last plan's per-phase
        # wall times (obs/ heartbeat pulls these; a handful of
        # perf_counter reads per tick, negligible against the tick budget)
        self.cache_hits = 0
        self.cache_misses = 0
        self.recompiles = 0
        self.last_phase_ms: Dict[str, float] = {}

    def _capacity(self, n: int) -> int:
        c = self.capacity_min
        while c < n:
            c *= 2
        return c

    def _drop_goal(self, g: int) -> int:
        """Evict one cached goal row: cache entry plus any dynamic-world
        host mirrors.  Returns the freed row index."""
        row = self.goal_rows.pop(g)
        self.dist_mirror.pop(g, None)
        self.dirs_mirror.pop(g, None)
        self.dist_seq.pop(g, None)
        if self.sector is not None:
            self.sector.forget(g)
            self.sector_hints.pop(g, None)
        return row

    def _store_mirror(self, g: int, dist_row: np.ndarray,
                      dirs_row: np.ndarray) -> None:
        """Keep one goal's repair mirrors, within budget (oldest-first
        eviction; an evicted goal's next repair full-recomputes) and as
        COPIES — a view would pin its whole sweep-chunk array long after
        the chunk-mates evict."""
        if g not in self.dist_mirror:
            while len(self.dist_mirror) >= self.max_mirrors:
                victim = next(iter(self.dist_mirror))
                self.dist_mirror.pop(victim)
                self.dirs_mirror.pop(victim, None)
                registry.get_registry().count("solverd.mirror_evictions")
        self.dist_mirror[g] = np.array(dist_row)
        self.dirs_mirror[g] = np.array(dirs_row)

    def _sweep_into_rows(self, goals: List[int], rows: List[int]) -> None:
        """Sweep ``goals`` in pow2 chunks no larger than FIELD_CHUNK
        (bounded program count: 1, 2, 4, 8) and scatter their packed
        rows into ``rows`` with ONE device scatter — each .at[].set on
        the preallocated buffer copies the whole cache, so a burst must
        not pay one copy per chunk.  The sub-chunk sizing matters on the
        CPU fallback, where one 8-wide sweep costs hundreds of ms — a
        single-goal call must not pay 8x padding waste.  In dynamic
        mode the host repair mirrors + staleness stamps record per
        goal.  Shared by the fresh-sweep path (_ensure_fields) and the
        repair full-recompute fallback (_repair_goals).  With the
        sector planner on, goals it can corridor-plan never reach the
        full sweep at all — _sector_sweep peels them off first."""
        if self.sector is not None:
            goals, rows = self._sector_sweep(goals, rows)
            if not goals:
                return
        parts = []
        o, c = 0, self.FIELD_CHUNK
        while o < len(goals):
            rem = len(goals) - o
            take = c if rem >= c else rem
            size = c if rem >= c else 1 << (take - 1).bit_length()
            chunk = goals[o:o + take]
            padded = chunk + [chunk[-1]] * (size - take)
            gvec = jnp.asarray(padded, jnp.int32)
            if self.keep_dist:
                packed, dist, dirs = self._fields_dist(self.free, gvec)
                parts.append(packed[:take])
                dist_np = np.asarray(dist[:take])
                dirs_np = np.asarray(dirs[:take])
                for j, g in enumerate(chunk):
                    self._store_mirror(g, dist_np[j], dirs_np[j])
            else:
                parts.append(self._fields(self.free, gvec)[:take])
            o += take
        for g in goals:
            self.dist_seq[g] = len(self.world_log)
        fields = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        self.dirs = self._pin_dirs(
            self.dirs.at[jnp.asarray(rows, jnp.int32)].set(fields))

    # -- hierarchical sector planning (ISSUE 19) --------------------------

    def _sector_hint(self, goal: int, pos: int) -> None:
        """Record one lane position as a corridor start for ``goal``'s
        next sector plan (no-op when the planner is off or the goal is
        the STAY pseudo-goal)."""
        if self.sector is None or goal == -1:
            return
        hs = self.sector_hints.setdefault(int(goal), set())
        if len(hs) < self.SECTOR_HINTS_MAX:
            hs.add(int(pos))

    def _sector_sweep(self, goals: List[int], rows: List[int]
                      ) -> Tuple[List[int], List[int]]:
        """Corridor-plan as many of ``goals`` as the planner can
        (consuming the start hints recorded at state-application time),
        scatter their packed rows in one device write, and return the
        remainder for the full-sweep path.  A goal with no recorded
        start (e.g. a prime prefetch before any lane holds it) falls
        back to the full sweep — that row is then whole-grid exact, so
        ``solverd.sector_fallbacks`` measures lost latency, never lost
        field quality."""
        reg = registry.get_registry()
        rem_g: List[int] = []
        rem_r: List[int] = []
        srows: List[int] = []
        packed: List[np.ndarray] = []
        for g, r in zip(goals, rows):
            starts = self.sector_hints.pop(g, ())
            plan = self.sector.plan_goal(g, starts)
            if plan is None:
                rem_g.append(g)
                rem_r.append(r)
                reg.count("solverd.sector_fallbacks")
                continue
            srows.append(r)
            packed.append(plan.packed)
            self.dist_seq[g] = len(self.world_log)
            reg.count("solverd.sector_routes")
            reg.observe("solverd.sector_plan_ms", self.sector.last_plan_ms)
        if srows:
            self.dirs = self._pin_dirs(
                self.dirs.at[jnp.asarray(srows, jnp.int32)].set(
                    jnp.asarray(np.stack(packed))))
        return rem_g, rem_r

    def _sector_reenter(self, goal: int, pos: int) -> None:
        """Extend ``goal``'s corridor when a lane reads STAY outside it:
        one plan_goal call folds the lane's cell (plus any hints that
        accumulated since the last plan) into the existing corridor —
        a portal route from the new start, never a world sweep — and
        rewrites the goal's cached row in place.  plan_goal always plans
        against the live mask at the planner's current epoch, so a
        re-entry also heals staleness and the world stamp advances."""
        if self.sector is None or not self.sector.manages(goal):
            return
        if not self.sector.needs_reentry(goal, pos):
            return
        starts = self.sector_hints.pop(goal, set()) | {int(pos)}
        plan = self.sector.plan_goal(goal, starts)
        if plan is None:
            return
        self.dist_seq[goal] = len(self.world_log)
        reg = registry.get_registry()
        reg.count("solverd.sector_reentries")
        reg.observe("solverd.sector_plan_ms", self.sector.last_plan_ms)
        self.dirs = self._pin_dirs(
            self.dirs.at[self.goal_rows[goal]].set(
                jnp.asarray(plan.packed)))

    def _is_stale(self, g: int) -> bool:
        """A cached row swept before the latest world toggle no longer
        matches the live mask (static runs: world_log stays empty and
        nothing is ever stale — zero overhead)."""
        if not self.world_log or g == -1:
            return False
        return self.dist_seq.get(g, -1) < len(self.world_log)

    def _ensure_fields(self, goals: List[int], min_rows: int = 0) -> None:
        missing = [g for g in dict.fromkeys(goals) if g not in self.goal_rows]
        rows_budget = max(self.max_fields,
                          self._capacity(max(len(goals), min_rows)))
        if self.dirs is None or self.dirs.shape[0] < rows_budget:
            # only grows on a capacity jump past the budget
            self._grow_dirs(rows_budget)
        if not missing:
            self._repair_stale(goals)
            return
        # evict LRU rows when over budget — never a goal of the current
        # request (they sit at the LRU tail because the caller touches
        # them first, and ``keep`` belt-and-braces that) nor a goal some
        # resident agent still references (goal_ref pin; this also covers
        # the permanent all-STAY pseudo-goal row, key -1)
        keep = set(goals)
        while len(self.goal_rows) + len(missing) > self.dirs.shape[0]:
            victim = next((g for g in self.goal_rows
                           if self.goal_ref.get(g, 0) == 0
                           and g not in keep), None)
            if victim is None:
                break
            self._drop_goal(victim)
        if len(self.goal_rows) + len(missing) > self.dirs.shape[0]:
            # every cached row is pinned by live goals: grow the buffer
            self._grow_dirs(self._capacity(len(self.goal_rows)
                                           + len(missing)))
        used = set(self.goal_rows.values())
        free_rows = [r for r in range(self.dirs.shape[0]) if r not in used]
        rows = free_rows[:len(missing)]
        self._sweep_into_rows(missing, rows)
        for g, r in zip(missing, rows):
            self.goal_rows[g] = r
        self._repair_stale(goals)

    def _repair_stale(self, goals: List[int]) -> None:
        stale = [g for g in dict.fromkeys(goals)
                 if g in self.goal_rows and self._is_stale(g)]
        if stale:
            self._repair_goals(stale)

    def _repair_goals(self, goals: List[int]) -> None:
        """Bring stale cached rows up to the live mask: bounded-region
        incremental repair (ops/field_repair.py) where a dist mirror and
        the toggle suffix exist, full recompute otherwise or when the
        dirty region overflows.  One batched device scatter for every
        repaired packed row."""
        reg = registry.get_registry()
        rows, packed_rows = [], []
        fallback = []
        h, _w = self.free_np.shape
        for g in goals:
            if g not in self.goal_rows or not self._is_stale(g):
                continue
            seq = self.dist_seq.get(g, -1)
            mirror = self.dist_mirror.get(g)
            res = None
            if mirror is not None and 0 <= seq <= len(self.world_log):
                t0 = time.perf_counter()
                res = field_repair.repair_field(mirror, self.free_np,
                                                self.world_log[seq:])
                reg.observe("solverd.field_repair_ms",
                            1000.0 * (time.perf_counter() - t0))
            if res is None:
                fallback.append(g)
                continue
            new_dist, (y0, y1, x0, x1) = res
            # direction codes change only where distances (or their row
            # neighbors') did: re-derive the band, repack the whole row
            # host-side (no device round trip)
            b0, b1 = max(0, y0 - 1), min(h, y1 + 1)
            dirs_m = self.dirs_mirror[g]
            if b1 > b0:
                dirs_m[b0:b1] = field_repair.directions_np(
                    new_dist, self.free_np, b0, b1)
            self.dist_mirror[g] = new_dist
            self.dist_seq[g] = len(self.world_log)
            rows.append(self.goal_rows[g])
            packed_rows.append(field_repair.pack_rows_np(
                dirs_m.reshape(-1)))
            reg.count("solverd.field_repairs")
            reg.count("solverd.field_sweeps", cause="repair")
        if rows:
            self.dirs = self._pin_dirs(
                self.dirs.at[jnp.asarray(rows, jnp.int32)].set(
                    jnp.asarray(np.stack(packed_rows))))
        if fallback:
            # full recompute repairs: recompute into the SAME rows (the
            # fresh-sweep path would allocate new ones), then re-mirror
            reg.count("solverd.field_repair_fallbacks", len(fallback))
            reg.count("solverd.field_sweeps", len(fallback),
                      cause="repair")
            self._sweep_into_rows(fallback,
                                  [self.goal_rows[g] for g in fallback])

    # -- stateless legacy path (JSON wire) --------------------------------

    def dispatch(self, agents: List[Tuple[str, int, int]]) -> PendingPlan:
        """Start one step for an explicit fleet; returns the un-synced
        device handles (see :class:`PendingPlan`)."""
        n = len(agents)
        cap = self._capacity(n)
        t_plan0 = time.perf_counter()
        goals = [g for _, _, g in agents]
        if self.sector is not None:
            # cached goals get a corridor re-entry check for each agent
            # position; fresh ones bank the positions as corridor starts
            # for the sweep below
            for _, p, g in agents:
                if g in self.goal_rows:
                    self._sector_reenter(g, int(p))
                else:
                    self._sector_hint(g, int(p))
        with trace.span("solverd.cache_lookup", agents=n,
                        parent="solverd.tick"):
            # counts hits/misses and LRU-touches cached request goals
            # FIRST so eviction inside _ensure_fields can only hit goals
            # absent from this request
            misses = self._count_cache(goals)
        t_sweep0 = time.perf_counter()
        if misses:
            registry.get_registry().count("solverd.field_sweeps", misses,
                                          cause="fresh_goal")
        with trace.span("solverd.field_sweep", fresh_goals=misses,
                        parent="solverd.tick"):
            self._ensure_fields(goals)
        t_disp0 = time.perf_counter()
        with trace.span("solverd.step_dispatch", capacity=cap,
                        parent="solverd.tick"):
            cfg = SolverConfig(height=self.grid.height, width=self.grid.width,
                               num_agents=cap)
            pos = np.zeros(cap, np.int32)
            goal = np.zeros(cap, np.int32)
            slot = np.zeros(cap, np.int32)
            active = np.zeros(cap, bool)
            # agents map onto cached field rows via the slot indirection;
            # padded lanes reuse row 0 but are masked inactive
            for k, (_, p, g) in enumerate(agents):
                pos[k], goal[k], slot[k] = p, g, self.goal_rows[g]
                active[k] = True
            new_pos, new_goal, _ = self._step(
                cfg, jnp.asarray(pos), jnp.asarray(goal), jnp.asarray(slot),
                self.dirs, jnp.asarray(active))
        p = PendingPlan()
        p.mode = "legacy"
        p.agents = agents
        p.cap, p.n = cap, n
        p.new_pos, p.new_goal = new_pos, new_goal
        p.base_pos = p.base_goal = p.base_active = None
        p.t_plan0, p.t_sweep0, p.t_disp0 = t_plan0, t_sweep0, t_disp0
        p.t_disp_end = time.perf_counter()
        return p

    def fetch(self, p: PendingPlan):
        """Block on the device outputs of a dispatched step and finish the
        plan.  Legacy mode returns ``[(peer_id, next_cell, goal_cell)]``;
        resident mode returns ``(lanes, next_cells, goal_cells)`` int32
        arrays holding only the lanes that moved or changed goal."""
        t_sync0 = time.perf_counter()
        with trace.span("solverd.device_sync", parent="solverd.tick"):
            new_pos = np.asarray(p.new_pos)
            new_goal = np.asarray(p.new_goal)
        t_end = time.perf_counter()
        # Operator-visible recompile stalls (survivable — the manager keeps
        # its own tick and drops the stale seq — but they must not be
        # silent).  Detected via the jit cache size, which catches EVERY
        # retrace — capacity changes AND dirs-buffer growth — and stays
        # quiet on cache hits (e.g. shrinking back to a known capacity).
        new_cache = getattr(self._step, "_cache_size", lambda: None)()
        if new_cache is not None and new_cache > self._seen_programs:
            self.recompiles += 1
            trace.count("solverd.recompiles")
            trace.instant("solverd.recompile", capacity=p.cap,
                          field_rows=int(self.dirs.shape[0]))
            print(f"⏳ recompiled step program "
                  f"(capacity {self._last_cap} -> {p.cap}, "
                  f"{self.dirs.shape[0]} field rows): plan stalled "
                  f"{time.perf_counter() - p.t_plan0:.1f}s", flush=True)
            self._seen_programs = new_cache
        self._last_cap = p.cap
        self.last_phase_ms = {
            "cache_lookup": 1000.0 * (p.t_sweep0 - p.t_plan0),
            "field_sweep": 1000.0 * (p.t_disp0 - p.t_sweep0),
            "step_dispatch": 1000.0 * (p.t_disp_end - p.t_disp0),
            "device_sync": 1000.0 * (t_end - t_sync0),
        }
        if p.mode == "legacy":
            return [(p.agents[k][0], int(new_pos[k]), int(new_goal[k]))
                    for k in range(p.n)]
        changed = p.base_active & ((new_pos != p.base_pos)
                                   | (new_goal != p.base_goal))
        lanes = np.flatnonzero(changed).astype(np.int32)
        return (lanes, new_pos[lanes].astype(np.int32),
                new_goal[lanes].astype(np.int32))

    def plan(self, agents: List[Tuple[str, int, int]]
             ) -> List[Tuple[str, int, int]]:
        """agents: [(peer_id, pos_cell, goal_cell)] ->
        [(peer_id, next_cell, goal_cell)] after one TSWAP step."""
        return self.fetch(self.dispatch(agents))

    # -- device-resident fast path (packed wire) --------------------------

    def _resident_grow(self, lanes_needed: int) -> None:
        cap = self._capacity(max(lanes_needed, 1))
        if cap <= self.r_cap:
            return
        pad = cap - self.r_cap
        self.h_pos = np.concatenate([self.h_pos, np.zeros(pad, np.int32)])
        self.h_goal = np.concatenate([self.h_goal, np.zeros(pad, np.int32)])
        self.h_slot = np.concatenate([self.h_slot, np.zeros(pad, np.int32)])
        self.h_active = np.concatenate([self.h_active, np.zeros(pad, bool)])
        if self.d_pos is None:
            self.d_pos = jnp.zeros(cap, jnp.int32)
            self.d_goal = jnp.zeros(cap, jnp.int32)
            self.d_slot = jnp.zeros(cap, jnp.int32)
            self.d_active = jnp.zeros(cap, bool)
        else:
            zi = jnp.zeros(pad, jnp.int32)
            self.d_pos = jnp.concatenate([self.d_pos, zi])
            self.d_goal = jnp.concatenate([self.d_goal, zi])
            self.d_slot = jnp.concatenate([self.d_slot, zi])
            self.d_active = jnp.concatenate([self.d_active,
                                             jnp.zeros(pad, bool)])
        if self.mesh is not None:
            # growth is rare (O(log N) per fleet life): re-pin the lane
            # sharding the concatenation may have dropped
            self.d_pos = self.mesh.pin_lanes(self.d_pos)
            self.d_goal = self.mesh.pin_lanes(self.d_goal)
            self.d_slot = self.mesh.pin_lanes(self.d_slot)
            self.d_active = self.mesh.pin_lanes(self.d_active)
        self.r_cap = cap

    def _scatter_fn(self):
        if self._scatter is None:
            def scatter(pos, goal, slot, active, idx, vp, vg, vs, va):
                return (pos.at[idx].set(vp), goal.at[idx].set(vg),
                        slot.at[idx].set(vs), active.at[idx].set(va))
            kw = {"donate_argnums": (0, 1, 2, 3)} if self._scatter_donate \
                else {}
            if self.mesh is not None:
                # pinned output layout: scatters must never de-shard the
                # resident lane arrays between ticks
                ls = self.mesh.lane_sharding
                kw["out_shardings"] = (ls, ls, ls, ls)
            self._scatter = jax.jit(scatter, **kw)
        return self._scatter

    def _ref_goal(self, goal: int, delta: int) -> None:
        r = self.goal_ref.get(goal, 0) + delta
        if r > 0:
            self.goal_ref[goal] = r
        else:
            self.goal_ref.pop(goal, None)

    def _count_cache(self, goals: List[int]) -> int:
        uniq = dict.fromkeys(goals)
        misses = sum(1 for g in uniq if g not in self.goal_rows)
        hits = len(uniq) - misses
        self.cache_hits += hits
        self.cache_misses += misses
        trace.count("solverd.field_cache_hits", hits)
        trace.count("solverd.field_cache_misses", misses)
        for g in goals:
            if g in self.goal_rows:
                self.goal_rows.move_to_end(g)
        return misses

    def _pin_dirs(self, arr: jnp.ndarray) -> jnp.ndarray:
        """Keep the dirs cache row-sharded on the mesh (a no-op repin
        when the layout already matches; identity on the flat path).
        Every ``self.dirs = ...`` write funnels through this so eager
        scatters/patches can never silently de-shard the cache."""
        if self.mesh is None:
            return arr
        return self.mesh.pin_rows(arr)

    def _lane_put(self, np_arr) -> jnp.ndarray:
        """Host -> device upload of a per-lane vector, agent-axis
        sharded in mesh mode."""
        if self.mesh is None:
            return jnp.asarray(np_arr)
        return self.mesh.pin_lanes(np.asarray(np_arr))

    def _grow_dirs(self, rows: int) -> None:
        """Reallocate the dirs buffer at ``rows`` capacity, preserving
        existing rows (recompiles the step program, like a capacity
        jump)."""
        if self.mesh is not None:
            # row count must divide over the agent shards (shard_map)
            rows = self.mesh.round_rows(rows)
        pc = packed_cells(self.grid.num_cells)
        old = self.dirs
        self.dirs = jnp.full((rows, pc), PACKED_STAY, jnp.uint32)
        if old is not None:
            self.dirs = self.dirs.at[:old.shape[0]].set(old)
        self.dirs = self._pin_dirs(self.dirs)

    def _stay_row(self) -> int:
        """The permanent all-STAY row (pseudo-goal key -1, pinned): lanes
        whose field is still being swept park here for a tick or two."""
        row = self.goal_rows.get(-1)
        if row is not None:
            return row
        if self.dirs is None:
            self._ensure_fields([])  # allocates the dirs buffer
        used = set(self.goal_rows.values())
        row = next((r for r in range(self.dirs.shape[0]) if r not in used),
                   None)
        if row is None:
            # cache saturated: evict an unpinned LRU goal, else grow
            victim = next((g for g in self.goal_rows
                           if self.goal_ref.get(g, 0) == 0), None)
            if victim is not None:
                row = self._drop_goal(victim)
            else:
                row = self.dirs.shape[0]
                self._grow_dirs(self._capacity(row + 1))
        # a reused (previously evicted) row still holds its old field —
        # the reserved row must genuinely say STAY everywhere
        pc = packed_cells(self.grid.num_cells)
        self.dirs = self._pin_dirs(self.dirs.at[row].set(
            jnp.full((pc,), PACKED_STAY, jnp.uint32)))
        self.goal_rows[-1] = row
        self.goal_ref[-1] = 1  # never evicted, never swept
        return row

    def _unwait(self, lane: int) -> None:
        g = self.lane_wait.pop(lane, None)
        if g is not None:
            s = self.wait_lanes.get(g)
            if s is not None:
                s.discard(lane)
                if not s:
                    del self.wait_lanes[g]

    def _queue_goal(self, goal: int, cause: str,
                    front: bool = False) -> None:
        """Enqueue (or re-prioritize) one idle-window sweep.  A goal
        already queued keeps its ORIGINAL enqueue clock (ageing measures
        true time-in-queue) but upgrades to ``fresh_goal`` when a lane
        starts waiting on it."""
        e = self.field_queue.get(goal)
        if e is None:
            self.field_queue[goal] = FieldQueueEntry(cause,
                                                     self.queue_clock)
        elif cause == "fresh_goal":
            e.cause = cause
        if front:
            self.field_queue.move_to_end(goal, last=False)

    def _queue_gauges(self) -> None:
        reg = registry.get_registry()
        reg.gauge("solverd.field_queue", len(self.field_queue))
        reg.gauge("solverd.field_queue_max_age",
                  max((self.queue_clock - e.enq
                       for e in self.field_queue.values()), default=0))

    def _pop_field_queue(self, budget: int) -> List[Tuple[int, "FieldQueueEntry"]]:
        """Pop up to ``budget`` queued sweeps, oldest-starved first: any
        entry older than FIELD_QUEUE_MAX_AGE process calls jumps the
        whole queue (fresh-goal churn front-inserts on every tick and
        would otherwise starve repair/prime entries forever)."""
        self.queue_clock += 1
        aged = [g for g, e in self.field_queue.items()
                if self.queue_clock - e.enq > self.FIELD_QUEUE_MAX_AGE]
        # promote oldest to the very front (front-insertion reverses, so
        # iterate youngest-first)
        for g in sorted(aged, key=lambda g: self.field_queue[g].enq,
                        reverse=True):
            self.field_queue.move_to_end(g, last=False)
        if aged:
            registry.get_registry().count("solverd.field_queue_promotions",
                                          len(aged))
        popped = []
        while self.field_queue and len(popped) < budget:
            popped.append(self.field_queue.popitem(last=False))
        self._queue_gauges()
        return popped

    def _sweep_popped(self, popped) -> None:
        """Shared idle-window work for popped queue entries: sweep the
        missing rows, repair the stale ones, count per cause."""
        reg = registry.get_registry()
        missing = [g for g, _ in popped if g not in self.goal_rows]
        by_cause: Dict[str, int] = {}
        for g, e in popped:
            # cached-but-stale entries are counted by _repair_goals
            # (cause=repair, whatever cause queued them) — counting them
            # here too would double-report the one repair performed
            if g not in self.goal_rows:
                by_cause[e.cause] = by_cause.get(e.cause, 0) + 1
        for cause, n in by_cause.items():
            if cause != "repair":
                reg.count("solverd.field_sweeps", n, cause=cause)
        if missing:
            with trace.span("solverd.field_prefetch", goals=len(missing)):
                self._ensure_fields(missing, min_rows=len(self.goal_ref))
            reg.count("solverd.prefetched_fields", len(missing))
        self._repair_stale([g for g, _ in popped])

    def _slot_of(self, lane: int, goal: int,
                 pos: Optional[int] = None) -> int:
        """Field row for a lane's goal; with deferred fields on, a missing
        row parks the lane on the STAY row and queues the sweep (front of
        the queue: a waiting agent outranks speculative prefetch).  A
        stale cached row (world toggle since its sweep) serves as-is —
        the STAY safety patch keeps it wall-legal — with its repair
        queued for the idle window.  ``pos`` (when the caller knows it)
        feeds the sector planner: a corridor start hint for a goal not
        yet planned, a re-entry check for one that is."""
        self._unwait(lane)
        if pos is not None:
            self._sector_hint(goal, pos)
        row = self.goal_rows.get(goal)
        if row is not None:
            if pos is not None:
                self._sector_reenter(goal, int(pos))
            if self._is_stale(goal):
                self._queue_goal(goal, "repair")
            return row
        self.lane_wait[lane] = goal
        self.wait_lanes.setdefault(goal, set()).add(lane)
        self._queue_goal(goal, "fresh_goal", front=True)
        return self._stay_row()

    def prefetch_goals(self, cells) -> None:
        """Queue future goals (manager hints: e.g. delivery cells at task
        assignment) for the idle-window sweep, so the field is resident
        long before the pickup->delivery flip makes it live."""
        for g in cells:
            try:
                g = int(g)
            except (TypeError, ValueError):
                continue
            if 0 <= g < self.grid.num_cells and g not in self.goal_rows \
                    and g not in self.field_queue:
                self._queue_goal(g, "prime")
        self._queue_gauges()

    def process_field_queue(self, max_goals: Optional[int] = None) -> int:
        """Sweep up to one chunk of queued goal fields (called from the
        daemon's idle window, NOT the tick path) and repair lanes parked
        on the STAY row.  Returns goals processed."""
        if not self.field_queue:
            return 0
        budget = max_goals or self.FIELD_CHUNK
        popped_entries = self._pop_field_queue(budget)
        self._sweep_popped(popped_entries)
        popped = [g for g, _ in popped_entries]
        # repair waiters for EVERY popped goal, not just freshly swept
        # ones — a goal can enter goal_rows through another request path
        # (e.g. a legacy JSON peer on the same daemon) while queued, and
        # its parked lanes must still be released
        lanes, slots = [], []
        for g in popped:
            for lane in sorted(self.wait_lanes.pop(g, ())):
                if self.lane_wait.get(lane) == g and self.h_active[lane] \
                        and int(self.h_goal[lane]) == g:
                    del self.lane_wait[lane]
                    lanes.append(lane)
                    slots.append(self.goal_rows[g])
                else:
                    self.lane_wait.pop(lane, None)
        if lanes:
            la = np.asarray(lanes, np.int32)
            vs = np.asarray(slots, np.int32)
            self.h_slot[la] = vs
            self._scatter_lanes(la, self.h_pos[la].copy(),
                                self.h_goal[la].copy(), vs,
                                self.h_active[la].copy())
        return len(popped)

    # -- dynamic world (ISSUE 9) ------------------------------------------

    def apply_world_update(self, toggles: List[Tuple[int, bool]]) -> int:
        """Fold one obstacle-toggle batch into the live mask.

        Returns the number of cells whose state actually changed.  Per
        accepted batch: the host+device masks update, every cached row
        gets a STAY safety patch so no stale field can point an agent
        INTO a newly blocked cell before its repair lands, live (pinned)
        cached goals enqueue ``repair`` sweeps for the idle window, and
        unpinned rows repair lazily on next touch (_slot_of)."""
        if not self.dynamic_world:
            return 0
        flat = self.free_np.reshape(-1)
        changed = []
        for c, blocked in toggles:
            c = int(c)
            if not 0 <= c < self.grid.num_cells:
                continue
            if bool(flat[c]) != (not blocked):
                flat[c] = not blocked
                changed.append((c, bool(blocked)))
        if not changed:
            return 0
        self.world_seq += 1
        self.keep_dist = True
        if len(self.world_log) + len(changed) > self.WORLD_LOG_MAX:
            # log compaction: drop history — every cached row becomes
            # fully stale and repairs via full recompute on next touch
            # (correct, just not incremental)
            self.world_log = []
            self.dist_seq = {}
            registry.get_registry().count("solverd.world_log_compactions")
        self.world_log.extend(c for c, _ in changed)
        self.free = jnp.asarray(self.free_np)
        if self.sector is not None:
            # the mask already mutated in place above — repair the
            # portal graph incrementally (dirty sectors + neighbors);
            # corridor plans re-derive through the normal staleness /
            # repair queue below
            t0 = time.perf_counter()
            n_sect = self.sector.apply_toggles([c for c, _ in changed])
            reg_s = registry.get_registry()
            reg_s.count("solverd.sector_rebuilds", n_sect)
            reg_s.observe("solverd.sector_repair_ms",
                          1000.0 * (time.perf_counter() - t0))
        newly_blocked = [c for c, b in changed if b]
        if newly_blocked and self.dirs is not None:
            self._stay_patch(newly_blocked)
        for g in list(self.goal_rows):
            if g != -1 and self.goal_ref.get(g, 0) > 0 \
                    and self._is_stale(g):
                self._queue_goal(g, "repair")
        self._queue_gauges()
        reg = registry.get_registry()
        reg.count("solverd.world_toggles", len(changed))
        reg.gauge("solverd.world_seq", self.world_seq)
        return len(changed)

    def _stay_patch(self, blocked_cells: List[int]) -> None:
        """Wall-safety overlay on EVERY cached packed row: a newly
        blocked cell's own code becomes STAY, and any neighbor whose
        code points INTO it becomes STAY (the lane waits in place until
        the exact repair computes the detour).  One gather + one scatter
        over the affected packed words across all rows."""
        h, w = self.free_np.shape
        # word index -> [(nibble, required_code | None)]; None forces STAY
        words: Dict[int, list] = {}
        for c in blocked_cells:
            words.setdefault(c >> 3, []).append((c & 7, None))
            cy, cx = divmod(c, w)
            for k, (dx, dy) in enumerate(DIR_DXDY):
                nx, ny = cx - dx, cy - dy  # neighbor whose code k lands on c
                if 0 <= nx < w and 0 <= ny < h:
                    n = ny * w + nx
                    words.setdefault(n >> 3, []).append((n & 7, k))
        cols = sorted(words)
        # np.asarray of a device buffer is read-only — copy before patching
        cur = np.array(self.dirs[:, jnp.asarray(cols, jnp.int32)])
        stay = np.uint32(DIR_STAY)
        for j, wi in enumerate(cols):
            for nib, req in words[wi]:
                shift = np.uint32(4 * nib)
                keep = np.uint32(0xFFFFFFFF) ^ (np.uint32(0xF) << shift)
                vals = (cur[:, j] >> shift) & np.uint32(0xF)
                hit = np.ones(cur.shape[0], bool) if req is None \
                    else vals == req
                patched = (cur[:, j] & keep) | (stay << shift)
                cur[:, j] = np.where(hit, patched, cur[:, j])
        self.dirs = self._pin_dirs(
            self.dirs.at[:, jnp.asarray(cols, jnp.int32)].set(
                jnp.asarray(cur)))
        # host dirs mirrors get the same overlay (repair re-derives the
        # exact band from the repaired distances later)
        for dirs_m in self.dirs_mirror.values():
            flat = dirs_m.reshape(-1)
            for c in blocked_cells:
                flat[c] = DIR_STAY
                cy, cx = divmod(c, w)
                for k, (dx, dy) in enumerate(DIR_DXDY):
                    nx, ny = cx - dx, cy - dy
                    if 0 <= nx < w and 0 <= ny < h:
                        n = ny * w + nx
                        if flat[n] == k:
                            flat[n] = DIR_STAY

    # -- audit plane (ISSUE 10) -------------------------------------------

    def set_corruption(self, lane: int, field: str = "goal",
                       delta: int = 1, view: str = "both") -> bool:
        """Register one sticky single-lane corruption (test hook for the
        injected-corruption drill): ``field`` of ``lane`` is forced to
        its current true value + ``delta`` after every state
        application.  ``view`` = "both" corrupts host mirror AND device
        (manager↔solverd roster divergence), "device" corrupts the
        device slab only (device↔mirror drift)."""
        lane = int(lane)
        if field not in ("pos", "goal") or view not in ("both", "device"):
            return False
        if lane >= self.r_cap or not self.h_active[lane]:
            return False
        true = int((self.h_pos if field == "pos" else self.h_goal)[lane])
        self.corrupt[lane] = (field, true + int(delta), view)
        registry.get_registry().count("solverd.audit_corruptions")
        self._apply_corruption()
        return True

    def _apply_corruption(self) -> None:
        for lane, (field, value, view) in self.corrupt.items():
            if lane >= self.r_cap or not self.h_active[lane]:
                continue
            if view != "device":
                (self.h_pos if field == "pos" else self.h_goal)[lane] = value
            vp = int(self.h_pos[lane])
            vg = int(self.h_goal[lane])
            if view == "device":
                if field == "pos":
                    vp = value
                else:
                    vg = value
            self._scatter_lanes(np.asarray([lane], np.int32),
                                np.asarray([vp], np.int32),
                                np.asarray([vg], np.int32),
                                np.asarray([int(self.h_slot[lane])],
                                           np.int32),
                                np.asarray([True]))

    def audit_views(self, view: str):
        """``(lanes, pos, goal)`` active-lane arrays of one audited view
        ("mirror" = host arrays, "device" = a device pull)."""
        if view == "device" and self.d_pos is not None:
            da = np.asarray(self.d_active)
            pos = np.asarray(self.d_pos)
            goal = np.asarray(self.d_goal)
        else:
            da, pos, goal = self.h_active, self.h_pos, self.h_goal
        act = np.flatnonzero(da)
        return act, pos[act], goal[act]

    def resident_shard_bytes(self, extra=()) -> Dict[int, int]:
        """Per-mesh-device resident bytes of the planning state (dirs
        cache + lane arrays + ``extra`` — e.g. the tenant slab planes).
        Empty on the flat path."""
        if self.mesh is None:
            return {}
        return self.mesh.shard_bytes(
            [self.dirs, self.d_pos, self.d_goal, self.d_slot,
             self.d_active, *extra])

    def update_mesh_gauges(self, extra=()) -> None:
        """Refresh the per-shard residency gauges (metadata only — no
        device sync; a no-op on the flat path).  The metrics beacon
        ships them; fleet_top's MESH line renders them."""
        per = self.resident_shard_bytes(extra)
        if not per:
            return
        reg = registry.get_registry()
        for k, b in per.items():
            reg.gauge("solverd.resident_bytes", b, shard=str(k))

    def _scatter_lanes(self, lanes, vp, vg, vs, va) -> None:
        """O(churn) device update: scatter per-lane values into the
        resident arrays, pow2-chunk-padded (see _pad_pow2_chunk)."""
        m = len(lanes)
        lanes, vp, vg, vs, va = _pad_pow2_chunk(
            self.SCATTER_CHUNK_MIN, lanes, vp, vg, vs, va)
        scatter = self._scatter_fn()
        self.d_pos, self.d_goal, self.d_slot, self.d_active = scatter(
            self.d_pos, self.d_goal, self.d_slot, self.d_active,
            jnp.asarray(lanes), jnp.asarray(vp), jnp.asarray(vg),
            jnp.asarray(vs), jnp.asarray(va))
        registry.get_registry().count("solverd.resident_scatter_lanes", m)

    def _ensure_rows_or_defer(self, goals: List[int]) -> None:
        """Inline sweep for fresh goals — unless deferred fields are on,
        in which case the tick path never sweeps (lanes park on the STAY
        row via _slot_of and the idle window catches up)."""
        misses = self._count_cache(goals)
        if self.defer_fields:
            return
        if misses:
            registry.get_registry().count("solverd.field_sweeps", misses,
                                          cause="fresh_goal")
        with trace.span("solverd.field_sweep", fresh_goals=misses,
                        parent="solverd.tick"):
            self._ensure_fields(goals, min_rows=len(self.goal_ref))

    def resident_apply(self, upd: "pcodec.DecodedUpdate") -> int:
        """Fold one decoded snapshot/delta into the resident fleet state;
        returns the number of lanes written."""
        reg = registry.get_registry()
        if upd.is_snapshot:
            lanes = upd.idx.astype(np.int64)
            self._resident_grow(int(lanes.max()) + 1 if lanes.size
                                else self.capacity_min)
            self.h_active[:] = False
            self.h_pos[:] = 0
            self.h_goal[:] = 0
            self.h_slot[:] = 0
            stay_pin = self.goal_ref.get(-1)
            self.goal_ref = {} if stay_pin is None else {-1: stay_pin}
            self.lane_wait = {}
            self.wait_lanes = {}
            goals = [int(g) for g in upd.goal]
            for g in goals:
                self._ref_goal(g, +1)
            if self.sector is not None:
                # corridor starts must be banked BEFORE the sweep below
                # plans the fresh goals
                for p, g in zip(upd.pos, goals):
                    self._sector_hint(g, int(p))
            self._ensure_rows_or_defer(goals)
            self.h_pos[lanes] = upd.pos
            self.h_goal[lanes] = upd.goal
            self.h_slot[lanes] = np.fromiter(
                (self._slot_of(int(l), g, int(p))
                 for l, g, p in zip(lanes, goals, upd.pos)),
                np.int32, len(goals))
            self.h_active[lanes] = True
            # a snapshot IS the O(N) resync: one full upload
            self.d_pos = self._lane_put(self.h_pos)
            self.d_goal = self._lane_put(self.h_goal)
            self.d_slot = self._lane_put(self.h_slot)
            self.d_active = self._lane_put(self.h_active)
            reg.count("solverd.snapshots_applied")
            self._apply_corruption()
            return int(lanes.size)
        # delta: one final value per lane (a lane can be vacated AND
        # re-assigned to a new peer in the same packet — last write wins,
        # matching PackedStateDecoder order)
        final: Dict[int, Optional[Tuple[int, int]]] = {}
        for lane in upd.removed:
            final[int(lane)] = None
        for lane, p, g in zip(upd.idx, upd.pos, upd.goal):
            final[int(lane)] = (int(p), int(g))
        if not final:
            return 0
        self._resident_grow(max(final) + 1)
        goals = []
        for lane, v in final.items():
            if self.h_active[lane]:
                self._ref_goal(int(self.h_goal[lane]), -1)
            if v is not None:
                self._ref_goal(v[1], +1)
                goals.append(v[1])
                self._sector_hint(v[1], v[0])
        self._ensure_rows_or_defer(goals)
        m = len(final)
        lanes = np.fromiter(final.keys(), np.int32, m)
        vp = np.zeros(m, np.int32)
        vg = np.zeros(m, np.int32)
        vs = np.zeros(m, np.int32)
        va = np.zeros(m, bool)
        for k, (lane, v) in enumerate(final.items()):
            if v is None:
                self._unwait(lane)
                continue
            vp[k], vg[k] = v
            vs[k] = self._slot_of(lane, v[1], v[0])
            va[k] = True
        self.h_pos[lanes] = vp
        self.h_goal[lanes] = vg
        self.h_slot[lanes] = vs
        self.h_active[lanes] = va
        self._scatter_lanes(lanes, vp, vg, vs, va)
        self._apply_corruption()
        return m

    def resident_dispatch(self) -> Optional[PendingPlan]:
        """Start one step over the device-resident fleet (no host->device
        upload beyond what deltas already scattered); None if no lanes are
        active."""
        n = int(self.h_active.sum())
        if n == 0:
            return None
        cap = self.r_cap
        t0 = time.perf_counter()
        with trace.span("solverd.step_dispatch", capacity=cap,
                        parent="solverd.tick"):
            cfg = SolverConfig(height=self.grid.height,
                               width=self.grid.width, num_agents=cap)
            new_pos, new_goal, _ = self._step(
                cfg, self.d_pos, self.d_goal, self.d_slot, self.dirs,
                self.d_active)
        p = PendingPlan()
        p.mode = "resident"
        p.agents = None
        p.cap, p.n = cap, n
        p.new_pos, p.new_goal = new_pos, new_goal
        # diff baselines: the resident mirrors AS OF this dispatch (the
        # pipelined loop may scatter the next delta before fetch())
        p.base_pos = self.h_pos.copy()
        p.base_goal = self.h_goal.copy()
        p.base_active = self.h_active.copy()
        p.t_plan0 = p.t_sweep0 = p.t_disp0 = t0
        p.t_disp_end = time.perf_counter()
        return p


def apply_world_frame(service: PlanService, reg, data: dict) -> int:
    """One ``world_update`` frame into the service — shared by the
    single-tenant TickRunner and the multi-tenant runner.  With
    JG_DYNAMIC_WORLD=0 the frame is counted and DROPPED (the static
    pipeline stays byte-identical)."""
    if not service.dynamic_world:
        reg.count("solverd.world_updates_ignored")
        return 0
    toggles = parse_world_update(data)
    if toggles is None:
        reg.count("solverd.bad_packets")
        return 0
    n = service.apply_world_update(toggles)
    reg.count("solverd.world_updates")
    # epoch adoption (ISSUE 10): the frame carries the manager's
    # monotone world_seq — adopt it so both sides' audit digests agree
    # on the epoch watermark (the local bump alone would drift after a
    # restart, where one replayed frame covers many original batches)
    ws = data.get("world_seq")
    if isinstance(ws, (int, float)) and int(ws) > service.world_seq:
        service.world_seq = int(ws)
        reg.gauge("solverd.world_seq", service.world_seq)
    if n:
        print(f"🌍 world_update (seq {data.get('world_seq')}): {n} "
              f"cell(s) toggled, {len(service.field_queue)} repair(s) "
              f"queued", flush=True)
    return n


# ---------------------------------------------------------------------------
# audit plane (ISSUE 10): digest entries, drill answering, corruption hook
# ---------------------------------------------------------------------------


def audit_entries(service: PlanService, seq: int
                  ) -> Tuple[list, dict]:
    """The flat daemon's audit-beacon body: host-mirror and device-pull
    lane digests at the last applied seq (their equality IS the
    device↔mirror consistency proof), plus the fresh field-cache cell
    digest keyed by the world epoch."""
    epoch = service.world_seq
    entries = []
    act, pos, goal = service.audit_views("mirror")
    d, n = obs_audit.lane_digest(act, pos, goal)
    entries.append(obs_audit.AuditEntry(obs_audit.SEC_MIRROR, n, seq,
                                        epoch, d))
    if service.d_pos is not None:
        dact, dpos, dgoal = service.audit_views("device")
        dd, dn = obs_audit.lane_digest(dact, dpos, dgoal)
        entries.append(obs_audit.AuditEntry(obs_audit.SEC_DEVICE, dn, seq,
                                            epoch, dd))
    fresh = [g for g in service.goal_rows
             if g != -1 and not service._is_stale(g)]
    fd, fn = obs_audit.cells_digest(fresh)
    entries.append(obs_audit.AuditEntry(obs_audit.SEC_FIELDS, fn, seq,
                                        epoch, fd))
    extra = {"dynamic_world": bool(service.dynamic_world),
             "epoch": epoch, "seq": seq}
    return entries, extra


def audit_drill_reply(service: PlanService, names, req: dict,
                      peer_id: str = "solverd") -> dict:
    """Range-digest (plus leaf rows) over one audited view of the
    resident fleet — the solverd side of the bisect protocol."""
    view = req.get("view") or "mirror"
    act, pos, goal = service.audit_views(
        "device" if view == "device" else "mirror")
    return obs_audit.drill_answer(req, act, pos, goal, names=names,
                                  peer_id=peer_id)


def handle_audit_frame(data: dict, service: PlanService, names,
                       bus, reg, peer_id: str = "solverd") -> bool:
    """Shared audit-plane frame handling for the flat daemon loop (drill
    requests + the env-gated corruption hook).  Returns True when the
    frame was an audit frame (handled or deliberately ignored)."""
    typ = data.get("type")
    if typ == "audit_drill_request":
        if data.get("target") in ("solverd", peer_id):
            bus.publish(obs_audit.AUDIT_TOPIC,
                        audit_drill_reply(service, names, data,
                                          peer_id=peer_id), raw=True)
        return True
    if typ == "audit_corrupt":
        if not obs_audit.hooks_enabled():
            # never a silent no-op: a drill harness must see its
            # injection refused rather than wait for a divergence that
            # can never come
            reg.count("solverd.audit_corrupt_ignored")
            print("🧪 audit_corrupt ignored (JG_AUDIT_TEST_HOOKS unset)",
                  flush=True)
            return True
        ok = service.set_corruption(int(data.get("lane", -1)),
                                    data.get("field") or "goal",
                                    int(data.get("delta") or 1),
                                    data.get("view") or "both")
        print(f"🧪 audit_corrupt lane={data.get('lane')} "
              f"field={data.get('field') or 'goal'} "
              f"view={data.get('view') or 'both'} applied={ok}",
              flush=True)
        return True
    if typ in ("audit_beacon", "audit_drill_response"):
        return True  # other peers' audit traffic on the shared topic
    return False


class PendingTick:
    """A tick in flight between :meth:`TickRunner.begin` and
    :meth:`TickRunner.finish` (its device step is dispatched, its response
    not yet encoded)."""

    __slots__ = ("req", "plan", "t_dispatched")


class TickRunner:
    """One solverd planning tick, decode -> plan -> encode — as a plain
    synchronous callable (:meth:`handle`: tests and simple drivers) or as
    the split :meth:`ingest` / :meth:`begin` / :meth:`finish` phases the
    pipelined daemon loop interleaves across requests.  Owns the tick
    span, the per-tick heartbeat line, and the on-demand stats snapshot
    (SIGUSR1 / bus stats_request)."""

    def __init__(self, service: PlanService, grid: Grid,
                 heartbeat: Optional[HeartbeatWriter] = None,
                 budget_ms: float = TICK_BUDGET_MS):
        self.service = service
        self.grid = grid
        self.heartbeat = heartbeat
        self.budget_ms = budget_ms
        self.ticks = 0
        self.dropped_total = 0
        self.registry = registry.get_registry()
        self.packed = pcodec.PackedStateDecoder()
        self.snapshot_needed = False
        self._req: Optional[dict] = None

    MAX_LANES = 1 << 20  # sanity ceiling on roster lanes (1M agents)

    def _packet_sane(self, pkt) -> bool:
        """Range-validate a decoded request packet: lanes within the sane
        roster ceiling, cells within this grid."""
        for a in (pkt.idx, pkt.named_idx, pkt.removed):
            if a.size and (int(a.min()) < 0
                           or int(a.max()) >= self.MAX_LANES):
                return False
        n_cells = self.grid.num_cells
        for a in (pkt.pos, pkt.goal):
            if a.size and (int(a.min()) < 0 or int(a.max()) >= n_cells):
                return False
        return True

    def ingest(self, data: dict, stale: bool = False) -> bool:
        """Decode one plan_request and fold it into solver state.  Packed
        deltas are order-sensitive, so superseded (stale-drained) packed
        requests are still APPLIED; stale JSON requests are skipped
        outright (stateless wire).  Returns True when ``data`` became the
        request to plan (:meth:`begin`)."""
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        if data.get("codec") == pcodec.CODEC_NAME:
            with trace.span("solverd.request_decode", parent="solverd.tick"):
                try:
                    raw = base64.b64decode(data.get("data") or "",
                                           validate=True)
                    pkt = pcodec.decode(raw)
                except (ValueError, pcodec.CodecError):
                    self.registry.count("solverd.bad_packets")
                    return False
                if pkt.trace is not None:
                    # trace1 block on the packed frame: the receive side
                    # of the manager->solverd hop (plan.request event +
                    # clock-skew-clamped one-way latency)
                    obs_events.emit("plan.request",
                                    trace_id=pkt.trace.trace_id,
                                    hop=pkt.trace.hop,
                                    send_ms=pkt.trace.send_ms,
                                    seq=data.get("seq"))
                if not self._packet_sane(pkt):
                    # a malformed-but-well-framed packet (bit flip, buggy
                    # peer) must not wrap negative lanes into live ones or
                    # allocate unbounded arrays — contain it like any
                    # other bad packet
                    self.registry.count("solverd.bad_packets")
                    return False
                self.registry.count("solverd.decode_bytes", len(raw))
                if pkt.kind == pcodec.KIND_DELTA:
                    # snapshots carry the whole fleet by design and have
                    # their own counter — folding them into delta_agents
                    # would overstate the O(churn) steady-state evidence
                    self.registry.count("solverd.delta_agents",
                                        int(pkt.idx.size))
                    self.registry.gauge("solverd.last_delta_agents",
                                        int(pkt.idx.size))
                prev_names = ({n for n in self.packed.names
                               if n is not None} if pkt.names else None)
                try:
                    upd = self.packed.apply(pkt)
                except pcodec.SeqGapError as e:
                    self.snapshot_needed = True
                    self.registry.count("solverd.seq_gaps")
                    trace.instant("solverd.seq_gap", have=e.have_seq,
                                  base=e.base_seq)
                    return False
                if prev_names is not None:
                    # lane-admission attribution (ISSUE 14): a newly
                    # named lane is an admitted agent — cause=handoff
                    # when the manager flagged it as a cross-region
                    # transfer, cause=fresh otherwise (snapshots re-
                    # declare the whole roster; prev_names keeps the
                    # count to genuine admissions)
                    handoff_names = set(data.get("handoff_peers") or [])
                    for n in pkt.names:
                        if n not in prev_names:
                            self.registry.count(
                                "solverd.lanes_admitted",
                                cause=("handoff" if n in handoff_names
                                       else "fresh"))
                self.service.resident_apply(upd)
                # manager hints (e.g. delivery cells at task assignment):
                # sweep their fields in the idle window, long before the
                # pickup flip makes them live goals
                self.service.prefetch_goals(data.get("hints") or [])
            if stale:
                return False
            caps = data.get("caps") or []
            self._req = {"mode": "packed", "seq": data.get("seq"),
                         "caps": caps, "t0": t0, "t0_ns": t0_ns,
                         "tc": pkt.trace, "t_dec": time.perf_counter()}
            if pcodec.CODEC_NAME not in caps:
                # JSON-response fallback: the pipelined loop ingests
                # request k+1 (mutating the roster) before finishing k,
                # so the names must be captured as of THIS request
                self._req["names"] = list(self.packed.names)
            return True
        if stale:
            return False  # stateless wire: only the newest matters
        with trace.span("solverd.request_decode", parent="solverd.tick"):
            agents = []
            w = self.grid.width
            for e in data.get("agents", []):
                px, py = e["pos"]
                gx, gy = e["goal"]
                agents.append((e["peer_id"], py * w + px, gy * w + gx))
        if not agents:
            self._req = None
            return False
        json_tc = obs_events.parse_tc(data)
        if json_tc is not None:
            obs_events.emit("plan.request", trace_id=json_tc[0],
                            hop=json_tc[1], send_ms=json_tc[2],
                            seq=data.get("seq"))
            json_tc = pcodec.TraceCtx(*json_tc)
        self._req = {"mode": "json", "seq": data.get("seq"),
                     "agents": agents, "t0": t0, "t0_ns": t0_ns,
                     "tc": json_tc, "t_dec": time.perf_counter()}
        return True

    def begin(self) -> Optional[PendingTick]:
        """Dispatch the device step for the last ingested request (no
        blocking on device outputs)."""
        r, self._req = self._req, None
        if r is None:
            return None
        if r["mode"] == "json":
            plan = self.service.dispatch(r["agents"])
        else:
            plan = self.service.resident_dispatch()
            if plan is None:
                return None
        p = PendingTick()
        p.req, p.plan = r, plan
        p.t_dispatched = time.perf_counter()
        return p

    def finish(self, pending: PendingTick,
               pipelined: bool = False) -> Optional[dict]:
        """Fetch the step outputs, encode and return the plan_response."""
        r, plan = pending.req, pending.plan
        t_fetch0 = time.perf_counter()
        # host time that ran concurrently with the device step (decode of
        # the next request, response publish, bus polling)
        overlap_ms = 1000.0 * (t_fetch0 - pending.t_dispatched)
        self.registry.observe("solverd.pipeline_overlap_ms", overlap_ms)
        result = self.service.fetch(plan)
        t_plan = time.perf_counter()
        # busy time only: decode+dispatch plus fetch — the pipeline's idle
        # overlap window is not the daemon's cost
        us = int(1e6 * ((pending.t_dispatched - r["t0"])
                        + (t_plan - t_fetch0)))
        with trace.span("solverd.reply_encode", parent="solverd.tick"):
            w = self.grid.width
            # echo the request's trace context one hop on (fresh send
            # stamp): the manager's plan.response event closes the loop
            resp_tc = None
            req_tc = r.get("tc")
            if req_tc is not None and obs_events.ctx_enabled():
                resp_tc = req_tc.next_hop()
            if r["mode"] == "json":
                resp = {
                    "type": "plan_response",
                    "seq": r["seq"],
                    "duration_micros": us,
                    "moves": [{"peer_id": pid,
                               "next_pos": [c % w, c // w],
                               "goal": [g % w, g // w]}
                              for pid, c, g in result],
                }
                if resp_tc is not None:
                    resp["tc"] = [resp_tc.trace_id, resp_tc.hop,
                                  resp_tc.send_ms]
            else:
                lanes, npos, ngoal = result
                if pcodec.CODEC_NAME in r["caps"]:
                    rpkt = pcodec.encode_response(r["seq"], lanes, npos,
                                                  ngoal)
                    rpkt.trace = resp_tc
                    resp = {
                        "type": "plan_response",
                        "seq": r["seq"],
                        "codec": pcodec.CODEC_NAME,
                        "duration_micros": us,
                        "data": pcodec.encode_b64(rpkt),
                    }
                else:
                    # packed request from a peer that cannot read packed
                    # responses: answer on the legacy wire via the roster
                    # AS OF this request (captured in ingest — the live
                    # roster may already reflect the next delta)
                    names = r.get("names") or []
                    moves = []
                    for lane, c, g in zip(lanes, npos, ngoal):
                        pid = names[int(lane)] \
                            if 0 <= int(lane) < len(names) else None
                        if pid is None:
                            continue
                        moves.append({"peer_id": pid,
                                      "next_pos": [int(c) % w, int(c) // w],
                                      "goal": [int(g) % w, int(g) // w]})
                    resp = {"type": "plan_response", "seq": r["seq"],
                            "duration_micros": us, "moves": moves}
                    if resp_tc is not None:
                        resp["tc"] = [resp_tc.trace_id, resp_tc.hop,
                                      resp_tc.send_ms]
        t_end = time.perf_counter()
        self.ticks += 1
        total_ms = 1000.0 * (t_end - r["t0"])
        # the tick span is stamped retroactively (phases carry an explicit
        # parent arg): in pipelined mode the phases of one tick interleave
        # with other requests' work, so no live span can wrap them — and
        # the span must be emitted BEFORE the heartbeat's flush either way
        trace.complete("solverd.tick",
                       r["t0_ns"], time.perf_counter_ns() - r["t0_ns"],
                       seq=r["seq"], pipelined=pipelined)
        # live tick accounting (always on): the fleet rollup's per-peer
        # tick p50/p95 vs the 500 ms budget comes from this histogram
        self.registry.observe("tick_ms", total_ms)
        if total_ms > self.budget_ms:
            self.registry.count("tick.over_budget")
        self.registry.gauge("tick.agents", plan.n)
        # mesh residency gauges (ISSUE 13): shard metadata only, no
        # device sync — a flat service returns before touching anything
        self.service.update_mesh_gauges()
        if self.heartbeat is not None:
            phase_ms = dict(self.service.last_phase_ms)
            phase_ms["decode"] = 1000.0 * (r["t_dec"] - r["t0"])
            phase_ms["encode"] = 1000.0 * (t_end - t_plan)
            if pipelined:
                phase_ms["overlap"] = overlap_ms
            phase_ms["total"] = total_ms
            self.heartbeat.beat(r["seq"], plan.n, phase_ms,
                                counters=trace.snapshot()["counters"])
            trace.flush()
        return resp

    def handle(self, data: dict) -> Optional[dict]:
        """plan_request dict -> plan_response dict (None for empty fleets
        or non-planning packets) — the synchronous decode->plan->encode
        path tests and simple drivers use."""
        if data.get("type") == "world_update":
            self.handle_world(data)
            return None
        pending = self.begin() if self.ingest(data) else None
        if pending is None:
            return None
        return self.finish(pending)

    def handle_world(self, data: dict) -> int:
        """Dynamic-world toggle frame (ISSUE 9): see apply_world_frame."""
        return apply_world_frame(self.service, self.registry, data)

    def stats(self) -> dict:
        """Machine-readable daemon state: tracer snapshot + service view."""
        svc = self.service
        snap = trace.snapshot()
        snap["service"] = {
            "ticks": self.ticks,
            "dropped_stale": self.dropped_total,
            "cache_hits": svc.cache_hits,
            "cache_misses": svc.cache_misses,
            "cached_fields": len(svc.goal_rows),
            "max_fields": svc.max_fields,
            "recompiles": svc.recompiles,
            "capacity": svc._last_cap,
            "resident_lanes": int(svc.h_active.sum()),
            "resident_capacity": svc.r_cap,
            "packed_last_seq": self.packed.last_seq,
            "defer_fields": svc.defer_fields,
            "field_queue": len(svc.field_queue),
            "deferred_lanes": len(svc.lane_wait),
            "dynamic_world": svc.dynamic_world,
            "world_seq": svc.world_seq,
            "world_log": len(svc.world_log),
            "dist_mirrors": len(svc.dist_mirror),
            "mesh": (None if svc.mesh is None else {
                "shape": svc.mesh.shape_str,
                "devices": svc.mesh.n_devices,
                "resident_bytes": svc.resident_shard_bytes()}),
            "last_phase_ms": {k: round(v, 3)
                              for k, v in svc.last_phase_ms.items()},
        }
        if self.heartbeat is not None:
            snap["service"]["over_budget_ticks"] = \
                self.heartbeat.over_budget_ticks
        # bandwidth snapshot (ISSUE 2 satellite): the registry is the single
        # source for bus accounting, so SIGUSR1 / stats_request dumps carry
        # the same wire-byte numbers the metrics beacons publish
        snap["network"] = self.registry.network_summary()
        return snap


# ---------------------------------------------------------------------------
# Multi-tenant device residency (ISSUE 8): ONE solverd serving many fleets.
#
# Each tenant (a whole fleet behind a bus namespace, runtime/busns.py) gets
# one ROW of a [T_cap, L_cap] device-resident super-batch — pow2-padded on
# both axes exactly like the single-tenant lane padding, so tenant churn
# and fleet growth cause O(log) recompiles.  One jitted vmapped step plans
# EVERY tenant's lanes in a single device call per tick burst; rows are
# physically isolated (vmap batching), so two tenants' agents can occupy
# the same cell of their separate worlds without interacting.  The
# direction-field cache is SHARED across tenants — all scenarios run the
# same grid, so tenant B hits the rows tenant A swept (the cross-tenant
# caching win) — with the existing refcount pinning counting every
# tenant's resident goals.
# ---------------------------------------------------------------------------


class Tenant:
    """One admitted fleet: its slab row, packed-delta decoder chain and
    admission bookkeeping."""

    __slots__ = ("ns", "topic", "row", "decoder", "last_req_ms",
                 "admitted_ms", "resyncs", "snapshot_needed")

    def __init__(self, ns: str, row: int):
        self.ns = ns
        self.topic = busns.wire_topic(ns, "solver")
        self.row = row
        self.decoder = pcodec.PackedStateDecoder()
        self.last_req_ms = time.monotonic() * 1000.0
        self.admitted_ms = self.last_req_ms
        self.resyncs = 0
        self.snapshot_needed = False


class PendingSuper:
    """A dispatched-but-unfetched super-batch step: device handles plus
    the per-tenant requests (and per-row diff baselines) its responses
    need.  Baselines are captured per REQUESTING row at dispatch time —
    not a whole-slab copy — so the memcpy cost scales with the burst,
    and a row evicted+reassigned while the step is in flight can never
    be diffed against another tenant's state."""

    __slots__ = ("new_pos", "new_goal", "bases", "reqs", "t0",
                 "t_disp_end", "lanes")


class TenantSlab:
    """[T_cap, L_cap] device-resident fleet state for many tenants,
    sharing one :class:`PlanService`'s direction-field cache (dirs rows,
    goal refcount pins, deferred-field queue).  The service's own flat
    single-tenant resident state stays untouched — the daemon runs one
    mode or the other."""

    def __init__(self, service: PlanService, grid: Grid,
                 tenant_lanes: int = 1 << 16):
        self.service = service
        self.grid = grid
        self.tenant_lanes = tenant_lanes  # per-tenant lane budget
        self.T_cap = 0
        self.L_cap = 0
        self.h_pos = np.zeros((0, 0), np.int32)
        self.h_goal = np.zeros((0, 0), np.int32)
        self.h_slot = np.zeros((0, 0), np.int32)
        self.h_active = np.zeros((0, 0), bool)
        self.d_pos = self.d_goal = self.d_slot = self.d_active = None
        self.rows_used: set = set()
        # deferred-field parking, keyed (row, lane) — the slab analog of
        # PlanService.lane_wait/wait_lanes
        self.lane_wait: Dict[Tuple[int, int], int] = {}
        self.wait_lanes: Dict[int, set] = {}
        self._vstep = None
        self._vstep_l = 0
        self._rowscatter = None
        self._rowset = None

    # -- geometry ---------------------------------------------------------
    def _grow(self, rows: int, lanes: int) -> None:
        """Ensure capacity for ``rows`` tenant rows x ``lanes`` lanes;
        pow2 padding on both axes, full re-upload on growth (rare,
        O(log) times over a fleet's life — deltas never come here)."""
        cap_t = max(self.T_cap, 1)
        while cap_t < rows:
            cap_t *= 2
        cap_l = max(self.L_cap, self.service.capacity_min)
        while cap_l < lanes:
            cap_l *= 2
        if cap_t <= self.T_cap and cap_l <= self.L_cap and self.T_cap:
            return
        grown = np.zeros((cap_t, cap_l), np.int32)
        grown[:self.h_pos.shape[0], :self.h_pos.shape[1]] = self.h_pos
        g_goal = np.zeros((cap_t, cap_l), np.int32)
        g_goal[:self.h_goal.shape[0], :self.h_goal.shape[1]] = self.h_goal
        g_slot = np.zeros((cap_t, cap_l), np.int32)
        g_slot[:self.h_slot.shape[0], :self.h_slot.shape[1]] = self.h_slot
        g_act = np.zeros((cap_t, cap_l), bool)
        g_act[:self.h_active.shape[0], :self.h_active.shape[1]] = \
            self.h_active
        self.h_pos, self.h_goal = grown, g_goal
        self.h_slot, self.h_active = g_slot, g_act
        self.T_cap, self.L_cap = cap_t, cap_l
        self._upload()
        registry.get_registry().gauge("solverd.slab_lanes", cap_t * cap_l)

    def _upload(self) -> None:
        """Full host->device resync (growth/admission/eviction — the
        structural edges; steady-state deltas use the row scatter).  In
        mesh mode the slab planes shard over the lane axis (ISSUE 13)."""
        mesh = self.service.mesh
        if mesh is None:
            self.d_pos = jnp.asarray(self.h_pos)
            self.d_goal = jnp.asarray(self.h_goal)
            self.d_slot = jnp.asarray(self.h_slot)
            self.d_active = jnp.asarray(self.h_active)
        else:
            self.d_pos = mesh.pin_slab(self.h_pos)
            self.d_goal = mesh.pin_slab(self.h_goal)
            self.d_slot = mesh.pin_slab(self.h_slot)
            self.d_active = mesh.pin_slab(self.h_active)

    def alloc_row(self) -> int:
        row = next((r for r in range(self.T_cap)
                    if r not in self.rows_used), None)
        if row is None:
            row = self.T_cap
            self._grow(self.T_cap + 1, max(self.L_cap, 1))
        self.rows_used.add(row)
        return row

    def free_row(self, row: int) -> None:
        """Evict a tenant's row: unpin its goals, clear its deferred
        parking, zero host + device state."""
        for lane in np.flatnonzero(self.h_active[row]):
            self.service._ref_goal(int(self.h_goal[row, lane]), -1)
        for key in [k for k in self.lane_wait if k[0] == row]:
            g = self.lane_wait.pop(key)
            s = self.wait_lanes.get(g)
            if s is not None:
                s.discard(key)
                if not s:
                    del self.wait_lanes[g]
        self.h_pos[row] = 0
        self.h_goal[row] = 0
        self.h_slot[row] = 0
        self.h_active[row] = False
        self._row_set(row)
        self.rows_used.discard(row)

    # -- jitted programs --------------------------------------------------
    def _step_fn(self):
        if self._vstep is None or self._vstep_l != self.L_cap:
            cfg = SolverConfig(height=self.grid.height,
                               width=self.grid.width,
                               num_agents=self.L_cap)
            if self.service.mesh is not None:
                # mesh mode (ISSUE 13): the vmapped super-step runs
                # under shard_map — per-row next-hop psums over the
                # shared row-sharded field cache, bit-identical
                self._vstep = self.service.mesh.make_slab_step(cfg)
            else:
                def one(pos, goal, slot, active, dirs):
                    return step_parallel(cfg, pos, goal, slot, dirs,
                                         active)

                # the super-batch: one program, tenants down the batch
                # axis, the shared field cache broadcast (in_axes=None)
                self._vstep = jax.jit(jax.vmap(one,
                                               in_axes=(0, 0, 0, 0, None)))
            self._vstep_l = self.L_cap
        return self._vstep

    def _slab_out_shardings(self) -> dict:
        """jit kwargs pinning slab outputs to the lane sharding in mesh
        mode (scatters must never de-shard the resident planes)."""
        mesh = self.service.mesh
        if mesh is None:
            return {}
        ss = mesh.slab_sharding
        return {"out_shardings": (ss, ss, ss, ss)}

    def _row_scatter_fn(self):
        if self._rowscatter is None:
            def sc(pos, goal, slot, active, row, idx, vp, vg, vs, va):
                return (pos.at[row, idx].set(vp), goal.at[row, idx].set(vg),
                        slot.at[row, idx].set(vs),
                        active.at[row, idx].set(va))
            self._rowscatter = jax.jit(sc, **self._slab_out_shardings())
        return self._rowscatter

    def _row_set_fn(self):
        if self._rowset is None:
            def st(pos, goal, slot, active, row, vp, vg, vs, va):
                return (pos.at[row].set(vp), goal.at[row].set(vg),
                        slot.at[row].set(vs), active.at[row].set(va))
            self._rowset = jax.jit(st, **self._slab_out_shardings())
        return self._rowset

    def _row_set(self, row: int) -> None:
        """Device row <- host mirror row (snapshot / eviction)."""
        if self.d_pos is None:
            return
        st = self._row_set_fn()
        self.d_pos, self.d_goal, self.d_slot, self.d_active = st(
            self.d_pos, self.d_goal, self.d_slot, self.d_active,
            row, jnp.asarray(self.h_pos[row]),
            jnp.asarray(self.h_goal[row]), jnp.asarray(self.h_slot[row]),
            jnp.asarray(self.h_active[row]))

    def _scatter_row_lanes(self, row, lanes, vp, vg, vs, va) -> None:
        """O(churn) device update of one tenant row, pow2-chunk-padded
        (the 2-D analog of PlanService._scatter_lanes; shared
        _pad_pow2_chunk keeps the padding invariant identical)."""
        m = len(lanes)
        lanes, vp, vg, vs, va = _pad_pow2_chunk(
            PlanService.SCATTER_CHUNK_MIN, lanes, vp, vg, vs, va)
        sc = self._row_scatter_fn()
        self.d_pos, self.d_goal, self.d_slot, self.d_active = sc(
            self.d_pos, self.d_goal, self.d_slot, self.d_active, row,
            jnp.asarray(lanes), jnp.asarray(vp), jnp.asarray(vg),
            jnp.asarray(vs), jnp.asarray(va))
        registry.get_registry().count("solverd.resident_scatter_lanes", m)

    # -- deferred fields (slab flavor) ------------------------------------
    def _unwait(self, row: int, lane: int) -> None:
        g = self.lane_wait.pop((row, lane), None)
        if g is not None:
            s = self.wait_lanes.get(g)
            if s is not None:
                s.discard((row, lane))
                if not s:
                    del self.wait_lanes[g]

    def _slot_of(self, row: int, lane: int, goal: int,
                 pos: Optional[int] = None) -> int:
        """Field row for a lane's goal; a missing row parks the lane on
        the shared STAY row and front-queues the sweep (a waiting agent
        outranks speculative prefetch).  Stale rows (world toggle since
        their sweep) queue a repair, like the flat path — which also
        owns the sector planner: hints and re-entry route through the
        shared service, so corridors fold starts across tenants."""
        svc = self.service
        self._unwait(row, lane)
        if pos is not None:
            svc._sector_hint(goal, pos)
        r = svc.goal_rows.get(goal)
        if r is not None:
            if pos is not None:
                svc._sector_reenter(goal, int(pos))
            if svc._is_stale(goal):
                svc._queue_goal(goal, "repair")
            return r
        self.lane_wait[(row, lane)] = goal
        self.wait_lanes.setdefault(goal, set()).add((row, lane))
        svc._queue_goal(goal, "fresh_goal", front=True)
        return svc._stay_row()

    def _ensure_rows_or_defer(self, goals: List[int]) -> None:
        svc = self.service
        misses = svc._count_cache(goals)
        if svc.defer_fields:
            return
        if misses:
            registry.get_registry().count("solverd.field_sweeps", misses,
                                          cause="fresh_goal")
        with trace.span("solverd.field_sweep", fresh_goals=misses,
                        parent="solverd.tick"):
            svc._ensure_fields(goals, min_rows=len(svc.goal_ref))

    def process_field_queue(self, max_goals: Optional[int] = None) -> int:
        """Idle-window sweep of queued goal fields + repair of slab lanes
        parked on the STAY row (the multi-tenant analog of
        PlanService.process_field_queue; popping, ageing promotion and
        per-cause counting are the SHARED service helpers)."""
        svc = self.service
        if not svc.field_queue:
            return 0
        budget = max_goals or PlanService.FIELD_CHUNK
        popped_entries = svc._pop_field_queue(budget)
        svc._sweep_popped(popped_entries)
        popped = [g for g, _ in popped_entries]
        by_row: Dict[int, List[Tuple[int, int]]] = {}
        for g in popped:
            for key in sorted(self.wait_lanes.pop(g, ())):
                row, lane = key
                if self.lane_wait.get(key) == g \
                        and self.h_active[row, lane] \
                        and int(self.h_goal[row, lane]) == g:
                    del self.lane_wait[key]
                    by_row.setdefault(row, []).append(
                        (lane, svc.goal_rows[g]))
                else:
                    self.lane_wait.pop(key, None)
        for row, pairs in by_row.items():
            la = np.asarray([p[0] for p in pairs], np.int32)
            vs = np.asarray([p[1] for p in pairs], np.int32)
            self.h_slot[row, la] = vs
            self._scatter_row_lanes(row, la, self.h_pos[row, la].copy(),
                                    self.h_goal[row, la].copy(), vs,
                                    self.h_active[row, la].copy())
        return len(popped)

    # -- state application ------------------------------------------------
    def apply(self, row: int, upd: "pcodec.DecodedUpdate") -> int:
        """Fold one decoded snapshot/delta into tenant ``row``'s slab
        slice (the multi-tenant port of PlanService.resident_apply);
        returns lanes written."""
        svc = self.service
        reg = registry.get_registry()
        if upd.is_snapshot:
            lanes = upd.idx.astype(np.int64)
            top = int(lanes.max()) + 1 if lanes.size else 1
            self._grow(max(len(self.rows_used), row + 1), top)
            for lane in np.flatnonzero(self.h_active[row]):
                svc._ref_goal(int(self.h_goal[row, lane]), -1)
            for key in [k for k in self.lane_wait if k[0] == row]:
                self._unwait(*key)
            self.h_active[row] = False
            self.h_pos[row] = 0
            self.h_goal[row] = 0
            self.h_slot[row] = 0
            goals = [int(g) for g in upd.goal]
            for g in goals:
                svc._ref_goal(g, +1)
            if svc.sector is not None:
                for p, g in zip(upd.pos, goals):
                    svc._sector_hint(g, int(p))
            self._ensure_rows_or_defer(goals)
            self.h_pos[row, lanes] = upd.pos
            self.h_goal[row, lanes] = upd.goal
            self.h_slot[row, lanes] = np.fromiter(
                (self._slot_of(row, int(l), g, int(p))
                 for l, g, p in zip(lanes, goals, upd.pos)),
                np.int32, len(goals))
            self.h_active[row, lanes] = True
            self._row_set(row)  # a snapshot IS the O(fleet) row resync
            reg.count("solverd.snapshots_applied")
            return int(lanes.size)
        final: Dict[int, Optional[Tuple[int, int]]] = {}
        for lane in upd.removed:
            final[int(lane)] = None
        for lane, p, g in zip(upd.idx, upd.pos, upd.goal):
            final[int(lane)] = (int(p), int(g))
        if not final:
            return 0
        self._grow(max(len(self.rows_used), row + 1), max(final) + 1)
        goals = []
        for lane, v in final.items():
            if self.h_active[row, lane]:
                svc._ref_goal(int(self.h_goal[row, lane]), -1)
            if v is not None:
                svc._ref_goal(v[1], +1)
                goals.append(v[1])
                svc._sector_hint(v[1], v[0])
        self._ensure_rows_or_defer(goals)
        m = len(final)
        lanes = np.fromiter(final.keys(), np.int32, m)
        vp = np.zeros(m, np.int32)
        vg = np.zeros(m, np.int32)
        vs = np.zeros(m, np.int32)
        va = np.zeros(m, bool)
        for k, (lane, v) in enumerate(final.items()):
            if v is None:
                self._unwait(row, lane)
                continue
            vp[k], vg[k] = v
            vs[k] = self._slot_of(row, lane, v[1], v[0])
            va[k] = True
        self.h_pos[row, lanes] = vp
        self.h_goal[row, lanes] = vg
        self.h_slot[row, lanes] = vs
        self.h_active[row, lanes] = va
        self._scatter_row_lanes(row, lanes, vp, vg, vs, va)
        return m

    # -- planning ---------------------------------------------------------
    def dispatch(self, reqs: Dict[str, dict],
                 rows: Dict[str, int]) -> Optional[PendingSuper]:
        """One vmapped device step over the WHOLE slab (every admitted
        tenant's lanes, responders and idlers alike — the step is
        stateless w.r.t. resident pos, so stepping a tenant without a
        pending request costs only masked compute); ``reqs`` maps tenant
        ns -> its ingested request, ``rows`` its slab row — the rows
        that get responses."""
        n = int(self.h_active.sum())
        if n == 0 or not reqs:
            return None
        t0 = time.perf_counter()
        with trace.span("solverd.step_dispatch", capacity=self.L_cap,
                        tenants=len(self.rows_used),
                        parent="solverd.tick"):
            step = self._step_fn()
            new_pos, new_goal, _ = step(self.d_pos, self.d_goal,
                                        self.d_slot, self.d_active,
                                        self.service.dirs)
        p = PendingSuper()
        p.new_pos, p.new_goal = new_pos, new_goal
        p.bases = {ns: (row, self.h_pos[row].copy(),
                        self.h_goal[row].copy(),
                        self.h_active[row].copy())
                   for ns, row in rows.items()}
        p.reqs = reqs
        p.lanes = n
        p.t0 = t0
        p.t_disp_end = time.perf_counter()
        reg = registry.get_registry()
        reg.gauge("solverd.superbatch_tenants", len(reqs))
        reg.gauge("solverd.superbatch_lanes", n)
        return p

    def fetch(self, p: PendingSuper) -> Tuple[np.ndarray, np.ndarray]:
        """Block on the super-step outputs; per-tenant diffs are cut by
        the runner against the dispatch-time baselines."""
        with trace.span("solverd.device_sync", parent="solverd.tick"):
            return np.asarray(p.new_pos), np.asarray(p.new_goal)


class MultiTenantRunner:
    """Admission, ingest and response encoding for the tenant slab.

    The daemon loop feeds it raw bus frames (wire topics — this runner
    and the slab are the only tenant-AWARE layer; managers and agents
    run unmodified behind their namespaces).  ``publish`` abstracts the
    bus so tests can drive the runner against a list."""

    def __init__(self, slab: TenantSlab, grid: Grid,
                 publish, max_tenants: int = 64,
                 idle_evict_ms: float = 2000.0,
                 heartbeat: Optional[HeartbeatWriter] = None,
                 budget_ms: float = TICK_BUDGET_MS):
        self.slab = slab
        self.grid = grid
        self.publish = publish
        self.max_tenants = max_tenants
        self.idle_evict_ms = idle_evict_ms
        self.heartbeat = heartbeat
        self.budget_ms = budget_ms
        self.tenants: Dict[str, Tenant] = {}
        self.pending_reqs: Dict[str, dict] = {}
        self.registry = registry.get_registry()
        self.ticks = 0
        self.dropped_total = 0

    MAX_LANES = TickRunner.MAX_LANES

    # -- admission / eviction --------------------------------------------
    def ensure_tenant(self, ns: str) -> Optional[Tenant]:
        t = self.tenants.get(ns)
        if t is not None:
            return t
        if len(self.tenants) >= self.max_tenants:
            victim = self._evictable()
            if victim is None:
                # everyone is actively planning: refuse rather than
                # thrash (the caller's requests drop until a slot idles)
                self.registry.count("solverd.tenant_admission_rejected")
                return None
            self.evict(victim, reason="lru")
        t = Tenant(ns, self.slab.alloc_row())
        self.tenants[ns] = t
        self.registry.count("solverd.tenant_admissions")
        self.registry.gauge("solverd.tenants", len(self.tenants))
        print(f"🏷️  tenant {ns or '<default>'} admitted "
              f"(row {t.row}, {len(self.tenants)} resident)", flush=True)
        return t

    def _evictable(self) -> Optional[Tenant]:
        """The least-recently-active tenant idle past the threshold."""
        now_ms = time.monotonic() * 1000.0
        idle = [t for t in self.tenants.values()
                if now_ms - t.last_req_ms >= self.idle_evict_ms]
        if not idle:
            return None
        return min(idle, key=lambda t: t.last_req_ms)

    def evict(self, t: Tenant, reason: str = "manual") -> None:
        """Release a tenant's device memory; its bus subscription stays,
        and the next plan_request re-admits it with a fresh decoder —
        whose seq gap triggers the plan_snapshot_request resync, so the
        manager (the system of record) rebuilds the row losslessly."""
        self.slab.free_row(t.row)
        self.tenants.pop(t.ns, None)
        self.pending_reqs.pop(t.ns, None)
        self.registry.count("solverd.tenant_evictions")
        self.registry.gauge("solverd.tenants", len(self.tenants))
        self.publish(t.topic, {"type": "tenant_evicted", "ns": t.ns,
                               "reason": reason})
        print(f"🏷️  tenant {t.ns or '<default>'} evicted ({reason}); "
              f"re-admission will snapshot-resync", flush=True)

    # -- ingest -----------------------------------------------------------
    def _packet_sane(self, pkt) -> bool:
        for a in (pkt.idx, pkt.named_idx, pkt.removed):
            if a.size and (int(a.min()) < 0
                           or int(a.max()) >= min(self.MAX_LANES,
                                                  self.slab.tenant_lanes)):
                return False
        n_cells = self.grid.num_cells
        for a in (pkt.pos, pkt.goal):
            if a.size and (int(a.min()) < 0 or int(a.max()) >= n_cells):
                return False
        return True

    def ingest(self, ns: str, data: dict, stale: bool = False) -> bool:
        """Decode one tenant's plan_request into its slab row.  Packed
        deltas are order-sensitive, so superseded requests still apply
        (``stale=True``); returns True when ``data`` became the
        tenant's request to answer this burst."""
        if data.get("codec") != pcodec.CODEC_NAME:
            # multi-tenant mode is packed-wire only — and an unservable
            # request must not ADMIT (a legacy-JSON manager would evict
            # a healthy idle tenant just to squat a slab row forever)
            self.registry.count("solverd.json_requests_ignored")
            return False
        t = self.ensure_tenant(ns)
        if t is None:
            return False
        t.last_req_ms = time.monotonic() * 1000.0
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        with trace.span("solverd.request_decode", parent="solverd.tick"):
            try:
                raw = base64.b64decode(data.get("data") or "",
                                       validate=True)
                pkt = pcodec.decode(raw)
            except (ValueError, pcodec.CodecError):
                self.registry.count("solverd.bad_packets")
                return False
            if pkt.trace is not None:
                obs_events.emit("plan.request", trace_id=pkt.trace.trace_id,
                                hop=pkt.trace.hop,
                                send_ms=pkt.trace.send_ms,
                                seq=data.get("seq"))
            if not self._packet_sane(pkt):
                self.registry.count("solverd.bad_packets")
                return False
            self.registry.count("solverd.decode_bytes", len(raw))
            if pkt.kind == pcodec.KIND_DELTA:
                self.registry.count("solverd.delta_agents",
                                    int(pkt.idx.size))
            try:
                upd = t.decoder.apply(pkt)
            except pcodec.SeqGapError as e:
                t.snapshot_needed = True
                self.registry.count("solverd.seq_gaps")
                trace.instant("solverd.seq_gap", have=e.have_seq,
                              base=e.base_seq, tenant=ns)
                return False
            self.slab.apply(t.row, upd)
            self.slab.service.prefetch_goals(data.get("hints") or [])
        if stale:
            return False
        caps = data.get("caps") or []
        req = {"ns": ns, "seq": data.get("seq"), "caps": caps,
               "t0": t0, "t0_ns": t0_ns, "tc": pkt.trace,
               "t_dec": time.perf_counter()}
        if pcodec.CODEC_NAME not in caps:
            req["names"] = list(t.decoder.names)
        self.pending_reqs[ns] = req
        return True

    def handle_world(self, data: dict) -> int:
        """Operator-plane dynamic-world toggle (ISSUE 9): the shared
        grid mutates for every tenant at once."""
        return apply_world_frame(self.slab.service, self.registry, data)

    def flush_snapshot_requests(self) -> None:
        for t in self.tenants.values():
            if t.snapshot_needed:
                t.snapshot_needed = False
                t.resyncs += 1
                self.registry.count("solverd.tenant_resyncs")
                self.publish(t.topic, {
                    "type": "plan_snapshot_request",
                    "have_seq": (t.decoder.last_seq
                                 if t.decoder.last_seq is not None
                                 else -1)})

    # -- plan / respond ---------------------------------------------------
    def begin(self) -> Optional[PendingSuper]:
        reqs, self.pending_reqs = self.pending_reqs, {}
        if not reqs:
            return None
        rows = {ns: self.tenants[ns].row for ns in reqs
                if ns in self.tenants}
        return self.slab.dispatch(reqs, rows)

    def finish(self, p: PendingSuper, pipelined: bool = False) -> None:
        """Fetch the super-step and publish one response per requesting
        tenant (packed when its request advertised the codec, legacy
        JSON otherwise)."""
        t_fetch0 = time.perf_counter()
        overlap_ms = 1000.0 * (t_fetch0 - p.t_disp_end)
        self.registry.observe("solverd.pipeline_overlap_ms", overlap_ms)
        new_pos, new_goal = self.slab.fetch(p)
        t_fetched = time.perf_counter()
        w = self.grid.width
        for ns, r in p.reqs.items():
            t = self.tenants.get(ns)
            base = p.bases.get(ns)
            if t is None or base is None or t.row != base[0]:
                continue  # evicted (or evicted+re-admitted) in flight
            row, base_pos, base_goal, base_active = base
            changed = base_active \
                & ((new_pos[row] != base_pos)
                   | (new_goal[row] != base_goal))
            lanes = np.flatnonzero(changed).astype(np.int32)
            npos = new_pos[row][lanes].astype(np.int32)
            ngoal = new_goal[row][lanes].astype(np.int32)
            us = int(1e6 * ((p.t_disp_end - r["t0"])
                            + (t_fetched - t_fetch0)))
            resp_tc = None
            if r.get("tc") is not None and obs_events.ctx_enabled():
                resp_tc = r["tc"].next_hop()
            with trace.span("solverd.reply_encode", parent="solverd.tick"):
                if pcodec.CODEC_NAME in r["caps"]:
                    rpkt = pcodec.encode_response(r["seq"], lanes, npos,
                                                  ngoal)
                    rpkt.trace = resp_tc
                    resp = {"type": "plan_response", "seq": r["seq"],
                            "codec": pcodec.CODEC_NAME,
                            "duration_micros": us,
                            "data": pcodec.encode_b64(rpkt)}
                else:
                    names = r.get("names") or []
                    moves = []
                    for lane, c, g in zip(lanes, npos, ngoal):
                        pid = names[int(lane)] \
                            if 0 <= int(lane) < len(names) else None
                        if pid is None:
                            continue
                        moves.append({"peer_id": pid,
                                      "next_pos": [int(c) % w, int(c) // w],
                                      "goal": [int(g) % w, int(g) // w]})
                    resp = {"type": "plan_response", "seq": r["seq"],
                            "duration_micros": us, "moves": moves}
                    if resp_tc is not None:
                        resp["tc"] = [resp_tc.trace_id, resp_tc.hop,
                                      resp_tc.send_ms]
            self.publish(t.topic, resp)
        self.ticks += 1
        first = min(r["t0"] for r in p.reqs.values())
        total_ms = 1000.0 * (time.perf_counter() - first)
        trace.complete("solverd.tick",
                       min(r["t0_ns"] for r in p.reqs.values()),
                       time.perf_counter_ns()
                       - min(r["t0_ns"] for r in p.reqs.values()),
                       tenants=len(p.reqs), pipelined=pipelined)
        self.registry.observe("tick_ms", total_ms)
        if total_ms > self.budget_ms:
            self.registry.count("tick.over_budget")
        self.registry.gauge("tick.agents", p.lanes)
        # mesh residency gauges (ISSUE 13): dirs cache + the slab planes
        self.slab.service.update_mesh_gauges(
            extra=(self.slab.d_pos, self.slab.d_goal, self.slab.d_slot,
                   self.slab.d_active))
        if self.heartbeat is not None:
            self.heartbeat.beat(
                self.ticks, p.lanes,
                {"total": total_ms,
                 "overlap": overlap_ms if pipelined else 0.0},
                counters=trace.snapshot()["counters"])
            trace.flush()

    def stats(self) -> dict:
        snap = trace.snapshot()
        svc = self.slab.service
        snap["service"] = {
            "mode": "multi_tenant",
            "ticks": self.ticks,
            "dropped_stale": self.dropped_total,
            "tenants": {
                (t.ns or "<default>"): {
                    "row": t.row,
                    "lanes": int(self.slab.h_active[t.row].sum())
                    if t.row < self.slab.T_cap else 0,
                    "last_seq": t.decoder.last_seq,
                    "resyncs": t.resyncs,
                    "idle_ms": round(time.monotonic() * 1000.0
                                     - t.last_req_ms, 1),
                } for t in self.tenants.values()},
            "slab": {"t_cap": self.slab.T_cap, "l_cap": self.slab.L_cap,
                     "lanes": int(self.slab.h_active.sum())},
            "cached_fields": len(svc.goal_rows),
            "max_fields": svc.max_fields,
            "cache_hits": svc.cache_hits,
            "cache_misses": svc.cache_misses,
            "defer_fields": svc.defer_fields,
            "field_queue": len(svc.field_queue),
            "deferred_lanes": len(self.slab.lane_wait),
            "dynamic_world": svc.dynamic_world,
            "world_seq": svc.world_seq,
            "mesh": (None if svc.mesh is None else {
                "shape": svc.mesh.shape_str,
                "devices": svc.mesh.n_devices,
                "resident_bytes": svc.resident_shard_bytes(
                    extra=(self.slab.d_pos, self.slab.d_goal,
                           self.slab.d_slot, self.slab.d_active))}),
        }
        snap["network"] = self.registry.network_summary()
        return snap


def audit_entries_tenant(slab: TenantSlab, tenant: Tenant
                         ) -> Tuple[list, dict]:
    """One tenant's audit-beacon body: its slab-row host-mirror and
    device-pull digests at ITS decoder seq (the manager behind this
    tenant's namespace publishes the matching shadow ring)."""
    svc = slab.service
    row = tenant.row
    seq = (tenant.decoder.last_seq
           if tenant.decoder.last_seq is not None else 0)
    epoch = svc.world_seq
    act = np.flatnonzero(slab.h_active[row])
    d, n = obs_audit.lane_digest(act, slab.h_pos[row][act],
                                 slab.h_goal[row][act])
    entries = [obs_audit.AuditEntry(obs_audit.SEC_MIRROR, n, seq,
                                    epoch, d)]
    if slab.d_pos is not None and row < slab.T_cap:
        dmask = np.asarray(slab.d_active[row])
        dact = np.flatnonzero(dmask)
        dd, dn = obs_audit.lane_digest(dact,
                                       np.asarray(slab.d_pos[row])[dact],
                                       np.asarray(slab.d_goal[row])[dact])
        entries.append(obs_audit.AuditEntry(obs_audit.SEC_DEVICE, dn, seq,
                                            epoch, dd))
    extra = {"dynamic_world": bool(svc.dynamic_world),
             "epoch": epoch, "seq": seq}
    return entries, extra


def tenant_audit_peer(ns: str) -> str:
    """The per-tenant audit peer id: one daemon publishes one digest
    stream per tenant, and the joiner keys streams by peer."""
    return f"solverd[{ns or 'default'}]"


def multi_tenant_loop(bus: BusClient, runner: MultiTenantRunner,
                      slab: TenantSlab, beacon,
                      stats_requested: dict, dump_stats) -> None:
    """The multi-tenant daemon loop: tenant-tagged ingest (wire topics
    carry the namespace), one pipelined vmapped super-step per request
    burst, per-tenant responses, dynamic admission via
    ``solver.admit``."""

    def subscribe_tenant(ns: str) -> None:
        bus.subscribe(busns.wire_topic(ns, "solver"), raw=True)

    svc = slab.service
    pending: Optional[PendingSuper] = None

    # audit plane (ISSUE 10): one digest stream PER TENANT (each joins
    # against its own namespaced manager's shadow ring) plus a shared
    # field-cache stream, all on the raw operator topic
    audit_on = obs_audit.enabled()
    audit_interval = obs_audit.interval_s()
    audit_state = {"last": 0.0, "effective": audit_interval}

    def audit_beat() -> None:
        if not audit_on:
            return
        now = time.monotonic()
        if audit_state["last"] \
                and now - audit_state["last"] < audit_state["effective"]:
            return
        audit_state["last"] = now
        t0 = time.perf_counter()
        ts_ms = time.time_ns() // 1_000_000
        payloads = []
        for t in list(runner.tenants.values()):
            entries, extra = audit_entries_tenant(slab, t)
            payloads.append({
                "type": "audit_beacon",
                "peer_id": tenant_audit_peer(t.ns),
                "proc": "solverd", "ns": t.ns, "pid": os.getpid(),
                "ts_ms": ts_ms,
                "caps": [obs_audit.AUDIT_CAP],
                "data": obs_audit.encode_audit_b64(entries),
                **extra})
        fresh = [g for g in svc.goal_rows
                 if g != -1 and not svc._is_stale(g)]
        fd, fn = obs_audit.cells_digest(fresh)
        payloads.append({
            "type": "audit_beacon", "peer_id": "solverd",
            "proc": "solverd", "ns": "", "pid": os.getpid(),
            "ts_ms": ts_ms,
            "caps": [obs_audit.AUDIT_CAP],
            "dynamic_world": bool(svc.dynamic_world),
            "epoch": svc.world_seq,
            "data": obs_audit.encode_audit_b64(
                [obs_audit.AuditEntry(obs_audit.SEC_FIELDS, fn, 0,
                                      svc.world_seq, fd)])})
        # self-throttle like AuditBeacon: per-tenant digest bodies
        # re-hash every slab row — cap audit overhead at ~2% of the
        # daemon loop by stretching the cadence when a beat runs long.
        # Publish AFTER the recompute so every stream advertises the
        # cadence this beat actually set (the joiner's silent threshold
        # is 3x the advertised value)
        audit_state["effective"] = max(
            audit_interval, 50.0 * (time.perf_counter() - t0))
        for p in payloads:
            p["interval_s"] = audit_state["effective"]
            bus.publish(obs_audit.AUDIT_TOPIC, p, raw=True)

    def handle_audit(data: dict) -> None:
        typ = data.get("type")
        if typ == "audit_drill_request":
            tns = data.get("ns") or ""
            t = runner.tenants.get(tns)
            if t is None or data.get("target") not in (
                    "solverd", tenant_audit_peer(tns)):
                return
            view = data.get("view") or "mirror"
            row = t.row
            if view == "device" and slab.d_pos is not None:
                mask = np.asarray(slab.d_active[row])
                pos = np.asarray(slab.d_pos[row])
                goal = np.asarray(slab.d_goal[row])
            else:
                mask, pos, goal = (slab.h_active[row], slab.h_pos[row],
                                   slab.h_goal[row])
            act = np.flatnonzero(mask)
            bus.publish(obs_audit.AUDIT_TOPIC, obs_audit.drill_answer(
                data, act, pos[act], goal[act], names=t.decoder.names,
                peer_id=tenant_audit_peer(tns)), raw=True)
        elif typ == "audit_corrupt":
            # the sticky corruption hook is a flat-daemon test fixture
            runner.registry.count("solverd.audit_corrupt_ignored")

    def route(frame) -> Optional[Tuple[str, dict]]:
        """(tenant ns, plan_request payload) of a frame, handling the
        control messages inline; None for everything else."""
        if frame.get("op") != "msg":
            return None
        data = frame.get("data") or {}
        topic = frame.get("topic") or ""
        ns, logical = busns.split_ns(topic)
        typ = data.get("type")
        if logical == obs_audit.AUDIT_TOPIC:
            # raw operator plane: drill requests resolve a tenant row via
            # the request's ns field; beacons from other peers are noise
            if ns == "":
                handle_audit(data)
            return None
        if logical == ADMIT_TOPIC:
            if typ == "tenant_hello" and isinstance(data.get("ns"), str):
                try:
                    hello_ns = busns.validate(data["ns"])
                except ValueError:
                    return None
                subscribe_tenant(hello_ns)
                if runner.ensure_tenant(hello_ns) is not None:
                    bus.publish(ADMIT_TOPIC,
                                {"type": "tenant_welcome", "ns": hello_ns})
            return None
        if logical != "solver":
            return None
        if typ == "stats_request":
            # cross-tenant stats enumerate EVERY tenant's namespace and
            # activity — operator tooling only: answered on the
            # un-namespaced topic, never into a tenant's namespace
            if ns == "":
                bus.publish(topic, {"type": "stats_response",
                                    **runner.stats()}, raw=True)
            return None
        if typ == "flight_dump":
            if ns != "":
                return None  # operator tooling, same rule as stats
            path = flightrec.dump(reason="bus_request")
            bus.publish(topic, {
                "type": "flight_dump_response", "proc": "solverd",
                "peer_id": "solverd", "path": path,
                "events": len(flightrec.get_recorder())}, raw=True)
            return None
        if typ == "world_update":
            # The grid is SHARED across every tenant's slab row, so only
            # the UN-NAMESPACED operator plane may mutate it — a single
            # tenant's manager must not re-shape every other fleet's
            # world.  (Namespaced C++ managers default dynamic-world OFF
            # for exactly this reason — their grids must not diverge
            # from a planner that drops their frames; per-tenant masks
            # are ROADMAP headroom.)
            if ns == "":
                runner.handle_world(data)
            else:
                runner.registry.count("solverd.world_updates_ignored")
            return None
        if typ != "plan_request":
            return None
        return ns, data

    while True:
        frame = bus.recv(timeout=0.002 if pending is not None
                         else (0.02 if svc.field_queue else 1.0))
        beacon.maybe_beat()
        audit_beat()
        if stats_requested["flag"]:
            stats_requested["flag"] = False
            dump_stats()
        if frame is None:
            if pending is not None:
                runner.finish(pending, pipelined=True)
                pending = None
            elif svc.field_queue:
                slab.process_field_queue()
            continue
        routed = route(frame)
        if routed is None:
            continue
        # stale drain, PER TENANT: every packed request applies in
        # order, only the newest per tenant is planned this burst.
        # BOUNDED: with many tenants ticking fast the inter-arrival gap
        # can stay under the drain timeout forever — an in-flight
        # step's responses must not be withheld behind an endless drain
        bursts: Dict[str, List[dict]] = {routed[0]: [routed[1]]}
        drained = 0
        while drained < 256:
            nxt = bus.recv(timeout=0.005)
            if nxt is None:
                break
            drained += 1
            r = route(nxt)
            if r is not None:
                bursts.setdefault(r[0], []).append(r[1])
        any_ok = False
        for ns, reqs in bursts.items():
            for stale_req in reqs[:-1]:
                runner.ingest(ns, stale_req, stale=True)
            if runner.ingest(ns, reqs[-1]):
                any_ok = True
            dropped = len(reqs) - 1
            if dropped:
                runner.dropped_total += dropped
                trace.count("solverd.dropped_stale", dropped)
        runner.flush_snapshot_requests()
        nxt_pending = runner.begin() if any_ok else None
        if pending is not None:
            runner.finish(pending, pipelined=True)
        pending = nxt_pending


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=7400)
    ap.add_argument("--map", default=None)
    ap.add_argument("--capacity-min", type=int, default=16)
    ap.add_argument("--warm", type=int, default=0,
                    help="pre-compile for an N-agent fleet before the "
                         "readiness banner (zero recompile stalls)")
    ap.add_argument("--trace", action="store_true",
                    help="force span tracing on (equivalent to JG_TRACE=1)")
    # Force the CPU backend (tests; also the env-var route is unreliable in
    # environments whose sitecustomize pre-imports jax with a plugin set).
    ap.add_argument("--cpu", action="store_true")
    # Mesh mode (ISSUE 13): shard the planning plane over a device mesh.
    # "N" = N-way agent-axis sharding (field rows + lanes), "AxT" adds a
    # grid-tile axis for the sweeps.  Unset/1 = today's single-device
    # path, byte-identical on the wire.
    ap.add_argument("--mesh", default=None,
                    help="device mesh spec N or AxT (JG_SOLVER_MESH); "
                         "unset/1 = single-device")
    # Multi-tenant mode (ISSUE 8): serve many namespaced fleets from one
    # device-resident super-batch.  --tenants pre-subscribes a static
    # tenant list; --multi-tenant additionally listens on solver.admit
    # for dynamic tenant_hello admission.  Either flag enables the mode.
    ap.add_argument("--tenants", default=None,
                    help="comma list of bus namespaces to serve "
                         "(JG_BUS_NS values; '' = the un-namespaced "
                         "default fleet)")
    ap.add_argument("--multi-tenant", action="store_true",
                    help="dynamic tenant admission via solver.admit")
    ap.add_argument("--max-tenants", type=int, default=64,
                    help="device-memory admission budget: tenants beyond "
                         "this evict the least-recently-active idle "
                         "tenant (snapshot-resync on re-admission)")
    ap.add_argument("--tenant-lanes", type=int, default=1 << 16,
                    help="per-tenant lane budget (requests addressing "
                         "lanes past it are rejected)")
    ap.add_argument("--tenant-idle-ms", type=float, default=2000.0,
                    help="a tenant is eviction-eligible only after this "
                         "long without a plan_request")
    # Federated world regions (ISSUE 14): each region pair runs its own
    # plan wire ("solver.r<id>", runtime/region.fed_solver_topic) so N
    # planning planes share one bus pool without cross-talk; --audit-ns
    # labels this daemon's audit beacons (e.g. "r0") so the auditor
    # joins it against ITS region's manager, not a neighbor's.
    ap.add_argument("--solver-topic",
                    default=os.environ.get("JG_SOLVER_TOPIC") or "solver",
                    help="plan-wire bus topic (JG_SOLVER_TOPIC; a "
                         "federated region pair uses solver.r<id>)")
    ap.add_argument("--audit-ns",
                    default=os.environ.get("JG_AUDIT_NS") or "",
                    help="audit-beacon pairing namespace (JG_AUDIT_NS; "
                         "federation uses the region label)")
    args = ap.parse_args(argv)
    tenant_list = ([busns.validate(t.strip()) for t in
                    args.tenants.split(",")] if args.tenants is not None
                   else [])
    multi_tenant = bool(tenant_list) or args.multi_tenant
    solver_topic = args.solver_topic
    if multi_tenant and solver_topic != "solver":
        # tenant plan wires are namespaced topics; a custom flat topic
        # would silently split the plane — fail loudly instead
        print("❌ --solver-topic is incompatible with multi-tenant mode",
              file=sys.stderr)
        return 2

    # Mesh spec (ISSUE 13): --mesh wins over JG_SOLVER_MESH; a malformed
    # spec is a startup error, never a silent single-device fallback.
    mesh_env = args.mesh if args.mesh is not None \
        else os.environ.get("JG_SOLVER_MESH")
    try:
        mesh_shape = solver_mesh.mesh_spec_from_env(mesh_env)
    except ValueError as e:
        print(f"❌ {e}", file=sys.stderr)
        return 2
    if mesh_shape is not None:
        # must precede the first CPU-client creation (jax.devices below):
        # on the CPU backend the mesh runs on virtual host devices (a
        # no-op env nudge for real multi-chip backends)
        virtual_mesh.force_virtual_cpu_devices(mesh_shape[0]
                                               * mesh_shape[1])

    tracer = trace.configure(enabled=True if args.trace else None,
                             proc="solverd")
    # lifecycle events + always-on flight recorder (ISSUE 5): SIGUSR2 /
    # crash / exit dumps, plus the bus flight_dump query handled below
    obs_events.configure("solverd")
    flightrec.install("solverd")

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if args.map:
        with open(args.map) as f:
            text = f.read()
        grid = (Grid.from_mapf_file(args.map) if text.startswith("type")
                else Grid.from_ascii(text))
    else:
        grid = Grid.default()

    # Subscribe BEFORE touching the device (including the jax.devices()
    # probe): accelerator init through the tunnel can take many seconds, and
    # plan_requests published meanwhile would be lost (the bus does not
    # replay).  The banner below is the readiness signal harnesses wait for.
    # reconnect=True: a busd restart must not kill the planning daemon —
    # it resubscribes and resumes answering plan_requests (the manager
    # plans natively during the gap via its failover path)
    # Multi-tenant solverd IS the cross-tenant infrastructure: its own
    # client must be un-namespaced no matter what JG_BUS_NS the spawning
    # environment exported (a fleet-wide env would otherwise prefix the
    # admit/solver subscriptions and merge that tenant into the default
    # row).  Single-tenant mode keeps the env behavior — a whole fleet
    # (solverd included) can legitimately live behind one namespace.
    bus = BusClient(port=args.port, peer_id="solverd", reconnect=True,
                    namespace="" if multi_tenant else None)
    if multi_tenant:
        # tenant plan wires are WIRE topics (the solverd client itself is
        # un-namespaced — it is the cross-tenant infrastructure)
        for ns in tenant_list:
            bus.subscribe(busns.wire_topic(ns, "solver"), raw=True)
        if args.multi_tenant:
            bus.subscribe(ADMIT_TOPIC)
        if "" not in tenant_list:
            bus.subscribe("solver")  # the un-namespaced default fleet
    else:
        bus.subscribe(solver_topic)
    if obs_audit.enabled():
        # audit plane (ISSUE 10): digest beacons + drill answering ride
        # the raw operator topic.  JG_AUDIT=0 skips the subscription AND
        # every frame — the wire stays byte-identical to pre-audit.
        bus.subscribe(obs_audit.AUDIT_TOPIC, raw=True)

    try:
        jax.devices()
    except RuntimeError as e:  # accelerator plugin failed: fall back to CPU
        print(f"⚠️ accelerator backend unavailable ({e}); falling back to CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        jax.devices()

    mesh_obj = None
    if mesh_shape is not None:
        try:
            mesh_obj = solver_mesh.SolverMesh(*mesh_shape)
            mesh_obj.validate_grid(grid)
        except (RuntimeError, ValueError) as e:
            print(f"❌ mesh {mesh_env}: {e}", file=sys.stderr)
            return 2
        reg = registry.get_registry()
        reg.gauge("solverd.mesh_devices", mesh_obj.n_devices)
        reg.gauge("solverd.mesh_agents", mesh_obj.n_agent_shards)
        reg.gauge("solverd.mesh_tiles", mesh_obj.n_tiles)
        # the shape string rides a labeled unit gauge (gauge values are
        # floats); the fleet aggregator lifts the label into its mesh
        # section
        reg.gauge("solverd.mesh_shape", 1, shape=mesh_obj.shape_str)

    service = PlanService(grid, capacity_min=args.capacity_min,
                          mesh=mesh_obj)
    if mesh_obj is not None:
        # residency gauges exist from the first beacon, not the first tick
        service.update_mesh_gauges()
    if args.warm:
        t0 = time.perf_counter()
        rng = np.random.default_rng(0)
        free_idx = np.flatnonzero(np.asarray(grid.free).reshape(-1))
        n = min(args.warm, len(free_idx) // 2)
        sel = rng.choice(free_idx, size=2 * n, replace=False)
        service.plan([(f"warm{k}", int(sel[k]), int(sel[n + k]))
                      for k in range(n)])
        # also pre-compile the small sweep chunk programs (1/2/4): steady
        # task churn arrives a goal or two per tick and must not pay a
        # first-use compile mid-fleet
        for size in (1, 2, 4):
            gvec = jnp.asarray([int(sel[0])] * size, jnp.int32)
            if service.keep_dist:
                # dynamic mode sweeps through the dist-returning variant
                # — warming the packed-only program would leave the live
                # path cold and pay the compile mid-fleet
                service._fields_dist(service.free, gvec)
            else:
                service._fields(service.free, gvec)
        print(f"🔥 pre-warmed: capacity {service._capacity(n)} step "
              f"program, field chunk programs, {n} field rows in "
              f"{time.perf_counter() - t0:.1f}s", flush=True)
    heartbeat = None
    if tracer.enabled:
        heartbeat = HeartbeatWriter(tracer.default_path("heartbeat"))
        print(f"🔎 tracing on: {tracer.default_path('trace')} "
              f"(+ heartbeat sidecar)", flush=True)
    runner = TickRunner(service, grid, heartbeat=heartbeat)
    mt_runner = slab = None
    if multi_tenant:
        slab = TenantSlab(service, grid, tenant_lanes=args.tenant_lanes)
        mt_runner = MultiTenantRunner(
            slab, grid,
            publish=lambda topic, data: bus.publish(topic, data, raw=True),
            max_tenants=args.max_tenants,
            idle_evict_ms=args.tenant_idle_ms, heartbeat=heartbeat)
        for ns in tenant_list:
            mt_runner.ensure_tenant(ns)

    # live-metrics plane: optional HTTP /metrics (JG_METRICS_PORT) and the
    # periodic registry beacon on bus topic mapd.metrics (fleet_top reads it)
    http_srv = registry.maybe_serve_http()
    if http_srv is not None:
        print(f"📡 /metrics on http://127.0.0.1:{http_srv.server_port}",
              flush=True)
    beacon = MetricsBeacon(bus, proc="solverd")
    audit_beacon = None
    if obs_audit.enabled() and not multi_tenant:
        audit_beacon = obs_audit.AuditBeacon(
            bus, "solverd",
            lambda: audit_entries(
                service,
                runner.packed.last_seq
                if runner.packed.last_seq is not None else 0),
            ns=args.audit_ns)

    # SIGUSR1 = operator stats dump: signal handlers only flip a flag (the
    # handler can interrupt the plan path mid-tick, where a full dump
    # would not be re-entrant); the loop below dumps between frames.
    stats_requested = {"flag": False}
    signal.signal(signal.SIGUSR1,
                  lambda *_: stats_requested.__setitem__("flag", True))

    def dump_stats() -> None:
        print("📈 stats " + json.dumps((mt_runner or runner).stats()),
              flush=True)
        trace.flush()

    def answer_stats() -> None:
        # on-demand machine-readable snapshot over the bus (the
        # operator-CLI / harness analog of SIGUSR1)
        bus.publish(solver_topic, {"type": "stats_response", **runner.stats()})
        trace.flush()

    trace.instant("solverd.up", port=args.port, multi_tenant=multi_tenant,
                  mesh=mesh_obj.shape_str if mesh_obj else None)
    print(f"🧮 solverd up on port {args.port} "
          f"(grid {grid.height}x{grid.width}, devices={jax.devices()}"
          + (f", mesh={mesh_obj.shape_str}"
             f" [{mesh_obj.n_devices} devices]" if mesh_obj else "")
          + (f", tenants={[t or '<default>' for t in tenant_list]}"
             f" max={args.max_tenants}" if multi_tenant else "") + ")")
    sys.stdout.flush()

    if multi_tenant:
        # the tenant-aware loop replaces the single-fleet one end to end
        multi_tenant_loop(bus, mt_runner, slab, beacon, stats_requested,
                          dump_stats)
        return 0

    # Pipelined tick loop (dispatch-then-poll): after dispatching the step
    # for request k the daemon returns to the bus instead of blocking on
    # the device — the decode of request k+1 and the publish of response k
    # overlap the device execution; the output fetch happens when the next
    # request arrives or a short poll timeout fires.
    pending: Optional[PendingTick] = None
    caps_logged = False
    while True:
        # short poll while a step is in flight; medium poll while queued
        # field sweeps wait for an idle window (they must run BETWEEN
        # ticks, not only when the bus goes fully silent for 1 s)
        frame = bus.recv(timeout=0.002 if pending is not None
                         else (0.02 if service.field_queue else 1.0))
        beacon.maybe_beat()  # ~2 s cadence riding the recv timeout
        if audit_beacon is not None:
            audit_beacon.maybe_beat()  # digest beacon, same cadence
        if not caps_logged and bus.hub_caps is not None:
            # relay-framing negotiation outcome (hub welcome), once —
            # operators can see at a glance whether responses ride the
            # hub's parse-free fast path or the legacy JSON relay
            caps_logged = True
            print(f"🚌 bus caps {bus.hub_caps}: relay fast framing "
                  f"{'on' if bus.fast_hub else 'off'}", flush=True)
        if stats_requested["flag"]:
            stats_requested["flag"] = False
            dump_stats()
        if frame is None:
            if pending is not None:
                resp = runner.finish(pending, pipelined=True)
                pending = None
                if resp is not None:
                    bus.publish(solver_topic, resp)
            elif service.field_queue:
                # idle window between ticks: sweep queued/prefetched goal
                # fields OFF the tick path (deferred field repair)
                service.process_field_queue()
            continue
        if frame.get("op") != "msg":
            continue
        data = frame.get("data") or {}
        if data.get("type") == "stats_request":
            answer_stats()
            continue
        if data.get("type") == "flight_dump":
            # black-box query: dump the ring and answer with the path
            path = flightrec.dump(reason="bus_request")
            bus.publish(solver_topic, {
                "type": "flight_dump_response", "proc": "solverd",
                "peer_id": "solverd", "path": path,
                "events": len(flightrec.get_recorder())})
            continue
        if data.get("type") == "world_update":
            # dynamic world (ISSUE 9): toggle the mask, STAY-patch the
            # cache, queue repairs — never stalls the tick path
            runner.handle_world(data)
            continue
        if obs_audit.enabled() and handle_audit_frame(
                data, service, runner.packed.names, bus,
                registry.get_registry()):
            # audit plane (ISSUE 10): drill requests answered from the
            # resident mirrors/device, corruption hook, peer noise
            continue
        if data.get("type") != "plan_request":
            continue
        # Staleness drop: if planning fell behind the manager's tick (slow
        # plan, recompile stall), requests queue up on the socket.  Only the
        # NEWEST is worth computing — the manager discards stale seqs anyway
        # (manager_centralized handle_plan_response) — so drain the queue
        # and plan once.  Packed deltas are order-sensitive: superseded
        # packed requests still fold into resident state (ingest stale=True)
        # before the newest is planned.
        reqs = [data]
        while True:
            # small positive timeout: 0.0 would flip the socket into
            # non-blocking mode, whose BlockingIOError recv() doesn't catch
            nxt = bus.recv(timeout=0.005)
            if nxt is None:
                break
            if nxt.get("op") != "msg":
                continue
            ndata = nxt.get("data") or {}
            if ndata.get("type") == "plan_request":
                reqs.append(ndata)
            elif ndata.get("type") == "stats_request":
                # a stats_request queued behind plan_requests must not be
                # swallowed by the stale drain — answer it right here
                answer_stats()
            elif ndata.get("type") == "world_update":
                # world toggles are ORDER-SENSITIVE against the deltas
                # around them and must not vanish in a drain either
                runner.handle_world(ndata)
            elif obs_audit.enabled() and str(
                    ndata.get("type") or "").startswith("audit_"):
                # a drill request queued behind plan_requests must be
                # answered, not swallowed by the stale drain
                handle_audit_frame(ndata, service, runner.packed.names,
                                   bus, registry.get_registry())
        for stale_req in reqs[:-1]:
            runner.ingest(stale_req, stale=True)
        ok = runner.ingest(reqs[-1])
        if runner.snapshot_needed:
            runner.snapshot_needed = False
            bus.publish(solver_topic, {
                "type": "plan_snapshot_request",
                "have_seq": (runner.packed.last_seq
                             if runner.packed.last_seq is not None else -1)})
            print("🔁 plan delta chain broken; requested full snapshot",
                  flush=True)
        dropped = len(reqs) - 1
        if dropped:
            runner.dropped_total += dropped
            trace.count("solverd.dropped_stale", dropped)
            print(f"⏭️  dropped {dropped} stale plan_request(s) "
                  f"({runner.dropped_total} total); planning seq "
                  f"{reqs[-1].get('seq')}", flush=True)
        nxt_pending = runner.begin() if ok else None
        if pending is not None:
            # request k+1 is already on the device; its decode (above) and
            # this fetch+encode+publish of response k are the overlap
            resp = runner.finish(pending, pipelined=True)
            if resp is not None:
                bus.publish(solver_topic, resp)
        pending = nxt_pending


if __name__ == "__main__":
    sys.exit(main())
