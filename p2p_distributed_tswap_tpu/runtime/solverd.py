"""solverd — the TPU solver daemon behind the centralized manager's
``--solver=tpu`` mode (the BASELINE.json north-star deployment shape).

The C++ centralized manager ships global agent state over bus topic "solver"
as a plan_request each planning tick; this daemon runs ONE batched TSWAP step
on the accelerator and replies with per-agent next positions (and possibly
swapped goals).  The manager stays the system of record — it converts moves
to move_instruction messages exactly as with its native solver.

Device-side design: fixed-capacity lanes (next power of two over the fleet
size) with the step kernel's ``active`` mask, so fleet growth causes at most
O(log N) recompiles; direction-field rows are cached per goal and recomputed
only for goals not seen before (LRU eviction), since TSWAP goal exchange
permutes goals far more often than the task lifecycle creates new ones.

Wire: plan_request  {type, seq, agents:[{peer_id, pos:[x,y], goal:[x,y]}]}
      plan_response {type, seq, duration_micros,
                     moves:[{peer_id, next_pos:[x,y], goal:[x,y]}]}
      (``goal`` in a move carries the step's swap/rotation decisions; the
      manager adopts them as TASK re-assignments — the task follows the
      exchanged goal and both Tasks are re-broadcast
      (manager_centralized adopt_goal_exchanges).  Round 4 ignored the
      returned goals, which livelocked head-on pairs: rotation, retreat,
      goal reset, repeat.)

Usage: python -m p2p_distributed_tswap_tpu.runtime.solverd
           [--port 7400] [--map FILE] [--capacity-min 16] [--warm N]
           [--trace]

Observability (obs/): with ``JG_TRACE=1`` (or ``--trace``) every tick is
traced phase-by-phase (decode -> cache lookup -> field sweep -> step
dispatch -> device sync -> encode) into Chrome trace-event JSONL plus a
per-tick heartbeat line judged against the manager's 500 ms planning
budget; ``kill -USR1`` or a bus ``stats_request`` message dumps a
machine-readable stats snapshot at any time (tracing not required).

``--warm N`` pre-compiles the whole planning path for an N-agent fleet
BEFORE the readiness banner: the step program at capacity(N), the
field-sweep chunk program, and N warm field rows.  A fleet started with
--warm sized to its agent count sees ZERO recompile stalls and never
trips the manager's native failover at startup (VERDICT r4 item 1: the
round-4 hardware run opened with a 77 s capacity-recompile stall).
"""

from __future__ import annotations

import argparse
import functools
import json
import signal
import sys
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.obs import HeartbeatWriter, registry, trace
from p2p_distributed_tswap_tpu.obs.beacon import MetricsBeacon
from p2p_distributed_tswap_tpu.obs.heartbeat import TICK_BUDGET_MS
from p2p_distributed_tswap_tpu.ops.distance import (
    PACKED_STAY,
    direction_fields,
    pack_directions,
    packed_cells,
)
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient
from p2p_distributed_tswap_tpu.solver.step import step_parallel


class PlanService:
    """Batched one-step planner with goal-field caching."""

    # Fresh-goal sweeps per jitted program call: new goals arrive a few per
    # tick (task churn), so a fixed small chunk keeps the program cached
    # while bounding padding waste.  The startup burst just loops chunks.
    FIELD_CHUNK = 8
    # Packed field-cache memory ceiling: rows are preallocated at FULL
    # budget up front so the step program's dirs shape never changes — the
    # round-3 stress run showed each cache-growth recompile stalling whole
    # ticks (tests/test_solverd_stress.py).
    CACHE_BYTES = 256 << 20

    def __init__(self, grid: Grid, capacity_min: int = 16,
                 field_cache: int = 4096):
        self.grid = grid
        self.free = jnp.asarray(grid.free)
        self.capacity_min = capacity_min
        pc = packed_cells(grid.num_cells)
        self.max_fields = max(capacity_min,
                              min(field_cache, self.CACHE_BYTES // (4 * pc)))
        # goal cell -> row index into the dirs buffer
        self.goal_rows: "OrderedDict[int, int]" = OrderedDict()
        self.dirs: jnp.ndarray | None = None  # (rows, ceil(HW/8)) packed uint32
        self._step = functools.partial(jax.jit, static_argnums=0)(step_parallel)
        # jitted fixed-chunk sweep: eager per-op dispatch of the doubling
        # scan cost ~5 s/tick on a 1-core host (stress test, round 3)
        self._fields = jax.jit(lambda goals: pack_directions(
            direction_fields(self.free, goals).reshape(goals.shape[0], -1)))
        self._last_cap = 0
        self._seen_programs = 0
        # observability: cumulative counters + the last plan's per-phase
        # wall times (obs/ heartbeat pulls these; a handful of
        # perf_counter reads per tick, negligible against the tick budget)
        self.cache_hits = 0
        self.cache_misses = 0
        self.recompiles = 0
        self.last_phase_ms: Dict[str, float] = {}

    def _capacity(self, n: int) -> int:
        c = self.capacity_min
        while c < n:
            c *= 2
        return c

    def _ensure_fields(self, goals: List[int]) -> None:
        missing = [g for g in dict.fromkeys(goals) if g not in self.goal_rows]
        pc = packed_cells(self.grid.num_cells)
        rows_budget = max(self.max_fields, self._capacity(len(goals)))
        if self.dirs is None or self.dirs.shape[0] < rows_budget:
            old = self.dirs
            self.dirs = jnp.full((rows_budget, pc), PACKED_STAY, jnp.uint32)
            if old is not None:  # only on a capacity jump past the budget
                self.dirs = self.dirs.at[:old.shape[0]].set(old)
        if not missing:
            return
        # evict LRU rows when over budget — never a goal of the current
        # request (they sit at the LRU tail because plan() touches them
        # before calling us, and the budget covers the request size)
        while len(self.goal_rows) + len(missing) > self.dirs.shape[0]:
            self.goal_rows.popitem(last=False)
        used = set(self.goal_rows.values())
        free_rows = [r for r in range(self.dirs.shape[0]) if r not in used]
        rows = free_rows[:len(missing)]
        c = self.FIELD_CHUNK
        # compute in fixed chunks (cached program), scatter ONCE: each
        # .at[].set on the preallocated buffer copies the whole cache, so a
        # startup burst must not pay one copy per chunk
        parts = []
        for o in range(0, len(missing), c):
            chunk = missing[o:o + c]
            padded = chunk + [chunk[-1]] * (c - len(chunk))
            parts.append(self._fields(jnp.asarray(padded,
                                                  jnp.int32))[:len(chunk)])
        fields = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        self.dirs = self.dirs.at[jnp.asarray(rows, jnp.int32)].set(fields)
        for g, r in zip(missing, rows):
            self.goal_rows[g] = r

    def plan(self, agents: List[Tuple[str, int, int]]
             ) -> List[Tuple[str, int, int]]:
        """agents: [(peer_id, pos_cell, goal_cell)] ->
        [(peer_id, next_cell, goal_cell)] after one TSWAP step."""
        n = len(agents)
        cap = self._capacity(n)
        # Operator-visible recompile stalls (survivable — the manager keeps
        # its own tick and drops the stale seq — but they must not be
        # silent).  Detected via the jit cache size, which catches EVERY
        # retrace — capacity changes AND dirs-buffer growth — and stays
        # quiet on cache hits (e.g. shrinking back to a known capacity).
        t_plan0 = time.perf_counter()
        goals = [g for _, _, g in agents]
        with trace.span("solverd.cache_lookup", agents=n):
            uniq = dict.fromkeys(goals)
            misses = sum(1 for g in uniq if g not in self.goal_rows)
            hits = len(uniq) - misses
            self.cache_hits += hits
            self.cache_misses += misses
            trace.count("solverd.field_cache_hits", hits)
            trace.count("solverd.field_cache_misses", misses)
            # LRU-touch cached request goals FIRST so eviction inside
            # _ensure_fields can only hit goals absent from this request
            for g in goals:
                if g in self.goal_rows:
                    self.goal_rows.move_to_end(g)
        t_sweep0 = time.perf_counter()
        with trace.span("solverd.field_sweep", fresh_goals=misses):
            self._ensure_fields(goals)
        t_disp0 = time.perf_counter()
        with trace.span("solverd.step_dispatch", capacity=cap):
            cfg = SolverConfig(height=self.grid.height, width=self.grid.width,
                               num_agents=cap)
            pos = np.zeros(cap, np.int32)
            goal = np.zeros(cap, np.int32)
            slot = np.zeros(cap, np.int32)
            active = np.zeros(cap, bool)
            # agents map onto cached field rows via the slot indirection;
            # padded lanes reuse row 0 but are masked inactive
            for k, (_, p, g) in enumerate(agents):
                pos[k], goal[k], slot[k] = p, g, self.goal_rows[g]
                active[k] = True
            new_pos, new_goal, _ = self._step(
                cfg, jnp.asarray(pos), jnp.asarray(goal), jnp.asarray(slot),
                self.dirs, jnp.asarray(active))
        t_sync0 = time.perf_counter()
        with trace.span("solverd.device_sync"):
            new_pos = np.asarray(new_pos)
            new_goal = np.asarray(new_goal)
        t_end = time.perf_counter()
        new_cache = getattr(self._step, "_cache_size", lambda: None)()
        if new_cache is not None and new_cache > self._seen_programs:
            self.recompiles += 1
            trace.count("solverd.recompiles")
            trace.instant("solverd.recompile", capacity=cap,
                          field_rows=int(self.dirs.shape[0]))
            print(f"⏳ recompiled step program "
                  f"(capacity {self._last_cap} -> {cap}, "
                  f"{self.dirs.shape[0]} field rows): plan stalled "
                  f"{time.perf_counter() - t_plan0:.1f}s", flush=True)
            self._seen_programs = new_cache
        self._last_cap = cap
        self.last_phase_ms = {
            "cache_lookup": 1000.0 * (t_sweep0 - t_plan0),
            "field_sweep": 1000.0 * (t_disp0 - t_sweep0),
            "step_dispatch": 1000.0 * (t_sync0 - t_disp0),
            "device_sync": 1000.0 * (t_end - t_sync0),
        }
        return [(agents[k][0], int(new_pos[k]), int(new_goal[k]))
                for k in range(n)]


class TickRunner:
    """One solverd planning tick, decode -> plan -> encode, as a plain
    callable — the daemon loop drives it with bus frames; tests drive it
    in-process with dicts.  Owns the tick span, the per-tick heartbeat
    line, and the on-demand stats snapshot (SIGUSR1 / bus stats_request)."""

    def __init__(self, service: PlanService, grid: Grid,
                 heartbeat: Optional[HeartbeatWriter] = None,
                 budget_ms: float = TICK_BUDGET_MS):
        self.service = service
        self.grid = grid
        self.heartbeat = heartbeat
        self.budget_ms = budget_ms
        self.ticks = 0
        self.dropped_total = 0
        self.registry = registry.get_registry()

    def handle(self, data: dict) -> Optional[dict]:
        """plan_request dict -> plan_response dict (None for empty fleets)."""
        seq = data.get("seq")
        t0 = time.perf_counter()
        with trace.span("solverd.tick", seq=seq):
            with trace.span("solverd.request_decode"):
                agents = []
                w = self.grid.width
                for e in data.get("agents", []):
                    px, py = e["pos"]
                    gx, gy = e["goal"]
                    agents.append((e["peer_id"], py * w + px, gy * w + gx))
                t_dec = time.perf_counter()
            if not agents:
                return None
            moves = self.service.plan(agents)
            t_plan = time.perf_counter()
            us = int((t_plan - t0) * 1e6)
            with trace.span("solverd.reply_encode"):
                resp = {
                    "type": "plan_response",
                    "seq": seq,
                    "duration_micros": us,
                    "moves": [{"peer_id": pid,
                               "next_pos": [c % w, c // w],
                               "goal": [g % w, g // w]}
                              for pid, c, g in moves],
                }
            t_end = time.perf_counter()
        self.ticks += 1
        total_ms = 1000.0 * (t_end - t0)
        # live tick accounting (always on): the fleet rollup's per-peer
        # tick p50/p95 vs the 500 ms budget comes from this histogram
        self.registry.observe("tick_ms", total_ms)
        if total_ms > self.budget_ms:
            self.registry.count("tick.over_budget")
        self.registry.gauge("tick.agents", len(agents))
        if self.heartbeat is not None:
            phase_ms = dict(self.service.last_phase_ms)
            phase_ms["decode"] = 1000.0 * (t_dec - t0)
            phase_ms["encode"] = 1000.0 * (t_end - t_plan)
            phase_ms["total"] = total_ms
            self.heartbeat.beat(seq, len(agents), phase_ms,
                                counters=trace.snapshot()["counters"])
            trace.flush()
        return resp

    def stats(self) -> dict:
        """Machine-readable daemon state: tracer snapshot + service view."""
        svc = self.service
        snap = trace.snapshot()
        snap["service"] = {
            "ticks": self.ticks,
            "dropped_stale": self.dropped_total,
            "cache_hits": svc.cache_hits,
            "cache_misses": svc.cache_misses,
            "cached_fields": len(svc.goal_rows),
            "max_fields": svc.max_fields,
            "recompiles": svc.recompiles,
            "capacity": svc._last_cap,
            "last_phase_ms": {k: round(v, 3)
                              for k, v in svc.last_phase_ms.items()},
        }
        if self.heartbeat is not None:
            snap["service"]["over_budget_ticks"] = \
                self.heartbeat.over_budget_ticks
        # bandwidth snapshot (ISSUE 2 satellite): the registry is the single
        # source for bus accounting, so SIGUSR1 / stats_request dumps carry
        # the same wire-byte numbers the metrics beacons publish
        snap["network"] = self.registry.network_summary()
        return snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=7400)
    ap.add_argument("--map", default=None)
    ap.add_argument("--capacity-min", type=int, default=16)
    ap.add_argument("--warm", type=int, default=0,
                    help="pre-compile for an N-agent fleet before the "
                         "readiness banner (zero recompile stalls)")
    ap.add_argument("--trace", action="store_true",
                    help="force span tracing on (equivalent to JG_TRACE=1)")
    # Force the CPU backend (tests; also the env-var route is unreliable in
    # environments whose sitecustomize pre-imports jax with a plugin set).
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    tracer = trace.configure(enabled=True if args.trace else None,
                             proc="solverd")

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if args.map:
        with open(args.map) as f:
            text = f.read()
        grid = (Grid.from_mapf_file(args.map) if text.startswith("type")
                else Grid.from_ascii(text))
    else:
        grid = Grid.default()

    # Subscribe BEFORE touching the device (including the jax.devices()
    # probe): accelerator init through the tunnel can take many seconds, and
    # plan_requests published meanwhile would be lost (the bus does not
    # replay).  The banner below is the readiness signal harnesses wait for.
    # reconnect=True: a busd restart must not kill the planning daemon —
    # it resubscribes and resumes answering plan_requests (the manager
    # plans natively during the gap via its failover path)
    bus = BusClient(port=args.port, peer_id="solverd", reconnect=True)
    bus.subscribe("solver")

    try:
        jax.devices()
    except RuntimeError as e:  # accelerator plugin failed: fall back to CPU
        print(f"⚠️ accelerator backend unavailable ({e}); falling back to CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        jax.devices()

    service = PlanService(grid, capacity_min=args.capacity_min)
    if args.warm:
        t0 = time.perf_counter()
        rng = np.random.default_rng(0)
        free_idx = np.flatnonzero(np.asarray(grid.free).reshape(-1))
        n = min(args.warm, len(free_idx) // 2)
        sel = rng.choice(free_idx, size=2 * n, replace=False)
        service.plan([(f"warm{k}", int(sel[k]), int(sel[n + k]))
                      for k in range(n)])
        print(f"🔥 pre-warmed: capacity {service._capacity(n)} step "
              f"program, field chunk program, {n} field rows in "
              f"{time.perf_counter() - t0:.1f}s", flush=True)
    heartbeat = None
    if tracer.enabled:
        heartbeat = HeartbeatWriter(tracer.default_path("heartbeat"))
        print(f"🔎 tracing on: {tracer.default_path('trace')} "
              f"(+ heartbeat sidecar)", flush=True)
    runner = TickRunner(service, grid, heartbeat=heartbeat)

    # live-metrics plane: optional HTTP /metrics (JG_METRICS_PORT) and the
    # periodic registry beacon on bus topic mapd.metrics (fleet_top reads it)
    http_srv = registry.maybe_serve_http()
    if http_srv is not None:
        print(f"📡 /metrics on http://127.0.0.1:{http_srv.server_port}",
              flush=True)
    beacon = MetricsBeacon(bus, proc="solverd")

    # SIGUSR1 = operator stats dump: signal handlers only flip a flag (the
    # handler can interrupt the plan path mid-tick, where a full dump
    # would not be re-entrant); the loop below dumps between frames.
    stats_requested = {"flag": False}
    signal.signal(signal.SIGUSR1,
                  lambda *_: stats_requested.__setitem__("flag", True))

    def dump_stats() -> None:
        print("📈 stats " + json.dumps(runner.stats()), flush=True)
        trace.flush()

    trace.instant("solverd.up", port=args.port)
    print(f"🧮 solverd up on port {args.port} "
          f"(grid {grid.height}x{grid.width}, devices={jax.devices()})")
    sys.stdout.flush()

    while True:
        frame = bus.recv(timeout=1.0)
        beacon.maybe_beat()  # ~2 s cadence riding the 1 s recv timeout
        if stats_requested["flag"]:
            stats_requested["flag"] = False
            dump_stats()
        if frame is None or frame.get("op") != "msg":
            continue
        data = frame.get("data") or {}
        if data.get("type") == "stats_request":
            # on-demand machine-readable snapshot over the bus (the
            # operator-CLI / harness analog of SIGUSR1)
            bus.publish("solver", {"type": "stats_response",
                                   **runner.stats()})
            trace.flush()
            continue
        if data.get("type") != "plan_request":
            continue
        # Staleness drop: if planning fell behind the manager's tick (slow
        # plan, recompile stall), requests queue up on the socket.  Only the
        # NEWEST is worth computing — the manager discards stale seqs anyway
        # (manager_centralized handle_plan_response) — so drain the queue
        # and plan once.
        dropped = 0
        while True:
            # small positive timeout: 0.0 would flip the socket into
            # non-blocking mode, whose BlockingIOError recv() doesn't catch
            nxt = bus.recv(timeout=0.005)
            if nxt is None:
                break
            if nxt.get("op") != "msg":
                continue
            ndata = nxt.get("data") or {}
            if ndata.get("type") == "plan_request":
                data = ndata
                dropped += 1
            elif ndata.get("type") == "stats_request":
                # a stats_request queued behind plan_requests must not be
                # swallowed by the stale drain — answer it right here
                bus.publish("solver", {"type": "stats_response",
                                       **runner.stats()})
        if dropped:
            runner.dropped_total += dropped
            trace.count("solverd.dropped_stale", dropped)
            print(f"⏭️  dropped {dropped} stale plan_request(s) "
                  f"({runner.dropped_total} total); planning seq "
                  f"{data.get('seq')}", flush=True)
        resp = runner.handle(data)
        if resp is not None:
            bus.publish("solver", resp)


if __name__ == "__main__":
    sys.exit(main())
