"""Multiplexed wire-faithful simulated agents: the fleetsim load pool.

``analysis/solver_crossover.py`` proved the pattern — N bus agents in
one process close the task/move loop over busd so the manager plans a
genuinely churning fleet — but its SimFleet was a harness-private
minimum: flat JSON heartbeats only, no trace context, no shard
awareness, no done retransmit.  This module is the reusable
generalization the load harness (``analysis/fleetsim.py``) drives to
thousands of agents per process:

- **wire-faithful**: each simulated agent mirrors the C++ centralized
  agent's protocol — adopt a dispatched Task, obey ``move_instruction``
  and re-broadcast position immediately, publish
  ``task_metric_completed`` + ``done`` at the delivery, retransmit the
  done until the manager's ``done_ack`` lands, drop a task on
  ``task_withdrawn``;
- **pos1/region-speaking**: with region gossip on (``JG_REGION_GOSSIP``,
  default), heartbeats are packed ``pos1`` beacons published on the
  agent's region topic ``mapd.pos.<rx>.<ry>`` — which the shard-aware
  BusClient routes to the owning busd shard, so a pool run loads the
  federated plane exactly like a real fleet.  A busy agent's beacon
  carries its task's trace1 context like the C++ agent's does;
- **trace-context-propagating**: the pool parses each task's ``tc``,
  max-merges hops from ``move_instruction``, and emits the same
  lifecycle events as the real agent (``task.claim`` / ``task.exec`` /
  ``task.delivery`` / ``task.done_ack`` via obs/events.py), so
  ``analysis/task_timeline.py`` attributes phases for simulated fleets
  with no special casing;
- **multiplexed identity**: thousands of agents share ONE BusClient
  (one socket per bus shard).  Identity travels in-band: heartbeats and
  dones carry an explicit ``peer_id`` payload field, which the
  centralized manager prefers over the bus frame's ``from`` when
  present (real per-process agents never set it — their wire is
  unchanged).

Heartbeats are staggered across the interval (agent index phase) so a
thousand-agent pool beacons as a smooth stream, not a thundering herd.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from p2p_distributed_tswap_tpu.obs import audit as _audit
from p2p_distributed_tswap_tpu.obs import events as _events
from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.runtime import plan_codec as pc
from p2p_distributed_tswap_tpu.runtime import region
from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient

DONE_RETRY_S = 2.0


def _now_ms() -> int:
    return time.time_ns() // 1_000_000


class SimAgent:
    """One simulated agent's protocol state (pure data; the pool drives
    it)."""

    __slots__ = ("peer_id", "pos", "task", "picked", "tc", "exec_emitted",
                 "next_hb", "unacked_done", "unacked_metric", "unacked_tc",
                 "done_next_retry")

    def __init__(self, peer_id: str, pos: int):
        self.peer_id = peer_id
        self.pos = pos
        self.task: Optional[dict] = None
        self.picked = False
        self.tc: Optional[pc.TraceCtx] = None
        self.exec_emitted = False
        self.next_hb = 0.0
        self.unacked_done: Optional[dict] = None
        self.unacked_metric: Optional[dict] = None
        self.unacked_tc: Optional[pc.TraceCtx] = None
        self.done_next_retry = 0.0


class SimAgentPool:
    """N wire-faithful agents multiplexed over one shard-aware client.

    ``port``/``host`` name the home bus shard; a pool environment
    (``JG_BUS_SHARD_PORTS``) makes the client shard-aware exactly like
    every other fleet process.  ``region_gossip``/``region_cells``
    default to the ``JG_REGION_GOSSIP``/``JG_REGION_CELLS`` environment
    (matching the C++ agents' knobs).
    """

    def __init__(self, n: int, side: int, port: int = 7400,
                 host: str = "127.0.0.1", seed: int = 1,
                 heartbeat_s: float = 2.0,
                 region_gossip: Optional[bool] = None,
                 region_cells: Optional[int] = None,
                 peer_id: str = "simfleet",
                 echo_moves: bool = True,
                 namespace: Optional[str] = None):
        import numpy as np

        self.n = n
        self.side = side
        self.heartbeat_s = heartbeat_s
        self.echo_moves = echo_moves
        self.region_gossip = (
            os.environ.get("JG_REGION_GOSSIP", "1") not in ("0", "false", "")
            if region_gossip is None else region_gossip)
        self.region_cells = int(
            region_cells if region_cells is not None
            else os.environ.get("JG_REGION_CELLS",
                                str(region.DEFAULT_REGION_CELLS))
            or region.DEFAULT_REGION_CELLS)
        rng = np.random.default_rng(seed)
        cells = rng.choice(side * side, size=n, replace=False)
        # peer ids shaped like the real fleet's (bus.hpp random_peer_id:
        # "12D3KooW" + 36 chars) — wire-byte realism (solver_crossover
        # established the discipline: short names flatter the codecs)
        alphabet = np.frombuffer(
            b"123456789ABCDEFGHJKLMNPQRSTUVWXYZ"
            b"abcdefghijkmnopqrstuvwxyz", np.uint8)

        def _pid(k: int) -> str:
            tail = rng.choice(alphabet, size=28).tobytes().decode()
            return f"12D3KooWsim{k:05d}{tail}"

        self.agents: Dict[str, SimAgent] = {}
        now = time.monotonic()
        for k in range(n):
            a = SimAgent(_pid(k), int(cells[k]))
            # stagger the first beat across the interval: smooth stream,
            # not a thundering herd of n beacons per interval edge
            a.next_hb = now + heartbeat_s * (k / max(1, n))
            self.agents[a.peer_id] = a
        # namespace: this pool's whole fleet lives behind one bus tenant
        # (ISSUE 8) — topics stay logical here, the client prefixes them
        self.bus = BusClient(host=host, port=port, peer_id=peer_id,
                             reconnect=True, namespace=namespace)
        self.bus.subscribe("mapd")
        # counters the harness reads after (or during) a run
        self.done_count = 0
        self.adopted = 0
        self.moves = 0
        self.withdrawn = 0
        self.acked = 0
        # replay plane (ISSUE 11): the outcome ledger the determinism
        # proof compares — WHICH task ids completed, and whether any id
        # completed more than once (two agents both delivering one task
        # is the "duplicated" half of "zero tasks lost or duplicated")
        self.done_ids: set = set()
        self.done_dups = 0
        self._task_specs_seen: set = set()
        # capture recorder (obs/capture.py): when attached, every
        # first-seen task and accepted world update is recorded as
        # replayable traffic
        self.capture = None
        # audit plane (ISSUE 10): the pool is the agent-side state
        # replica — it publishes a view digest (sorted held task ids)
        # on mapd.audit so the auditor can join it against the
        # manager's in-flight set.  JG_AUDIT=0 keeps the wire
        # byte-identical to the pre-audit pool.
        self.namespace = namespace or ""
        self._audit_beacon = _audit.AuditBeacon(
            self.bus, "simagent_pool", self._audit_entries,
            ns=self.namespace) if _audit.enabled() else None
        self.audit_beacons = 0
        # dynamic worlds (ISSUE 9): sim agents are move-obeying bodies —
        # routing around a toggled wall is the planner's job — but the
        # harness needs proof the frames propagated and what the manager
        # accepted
        self.world_updates = 0
        self.world_accepted = 0
        self.world_rejected = 0
        # capture evidence (ISSUE 11): the pool's run configuration goes
        # into the always-on flight ring, so a post-mortem capture
        # (blackbox --capture) can rebuild a replayable fleet config
        # from the rings alone — no trace_id means the ring records it
        # regardless of JG_TRACE/JG_TRACE_CTX
        _events.emit("capture.meta", agents=n, side=side, seed=seed,
                     heartbeat_s=heartbeat_s)

    # -- geometry ---------------------------------------------------------
    def _pt(self, c: int) -> List[int]:
        return [c % self.side, c // self.side]

    def _cell(self, p) -> int:
        return int(p[1]) * self.side + int(p[0])

    # -- publishing -------------------------------------------------------
    def _beacon(self, a: SimAgent) -> None:
        """One heartbeat: packed pos1 on the agent's region topic (the
        sharded-gossip wire) or flat JSON position_update — mirroring
        cpp/agent_centralized broadcast_position, identity in-band."""
        if self.region_gossip:
            tc = None
            if a.task is not None and a.tc is not None \
                    and _events.ctx_enabled():
                # current hop, fresh stamp: a repeated claim heartbeat
                tc = pc.TraceCtx(a.tc.trace_id, a.tc.hop, _now_ms())
            msg = {"type": "pos1", "peer_id": a.peer_id,
                   "data": pc.encode_pos1_b64(
                       a.pos, a.pos,
                       int(a.task["task_id"]) if a.task else None, tc)}
            topic = region.topic_for(a.pos % self.side, a.pos // self.side,
                                     self.region_cells)
            self.bus.publish(topic, msg)
            return
        msg = {"type": "position_update", "peer_id": a.peer_id,
               "position": self._pt(a.pos)}
        if a.task is not None:
            msg["busy_task"] = a.task["task_id"]
            if a.tc is not None and _events.ctx_enabled():
                msg["tc"] = [a.tc.trace_id, a.tc.hop, _now_ms()]
        self.bus.publish("mapd", msg)

    def _publish_done(self, a: SimAgent, now: float,
                      retransmit: bool = False) -> None:
        assert a.unacked_done is not None
        if retransmit and a.unacked_tc is not None:
            # retransmits carry a FRESH context stamp, hop advanced —
            # each retransmit is a new wire crossing (mirrors the C++
            # agent's refresh_unacked_tc); without this the retry delay
            # would read as multi-second wire latency in the timeline
            a.unacked_tc = pc.TraceCtx(a.unacked_tc.trace_id,
                                       a.unacked_tc.hop + 1, _now_ms())
            a.unacked_done["tc"] = [a.unacked_tc.trace_id,
                                    a.unacked_tc.hop,
                                    a.unacked_tc.send_ms]
        if a.unacked_metric is not None:
            self.bus.publish("mapd", a.unacked_metric)
        self.bus.publish("mapd", a.unacked_done)
        a.done_next_retry = now + DONE_RETRY_S

    def _arrival(self, a: SimAgent, now: float) -> None:
        t = a.task
        if t is None:
            return
        if a.pos == self._cell(t["pickup"]):
            a.picked = True  # stats only — see below
        # done detection is PURELY POSITIONAL, like the reference and the
        # C++ agent (completion_check: pos == delivery, no pickup gate).
        # This matters under TSWAP goal exchanges: a ToDelivery task
        # re-assigned mid-flight must complete when its NEW holder reaches
        # the delivery — gating on pickup-visited strands every exchanged
        # task and the fleet decays into exchange thrash (found by the
        # fleetsim SLO gate, tasks/s collapsing 121/min -> 2/min).
        if a.pos == self._cell(t["delivery"]):
            tid = int(t["task_id"])
            if a.tc is not None:
                _events.emit("task.delivery", trace_id=a.tc.trace_id,
                             hop=a.tc.hop, task_id=tid, peer=a.peer_id)
            done = {"status": "done", "task_id": tid, "peer_id": a.peer_id}
            if a.tc is not None and _events.ctx_enabled():
                a.tc = pc.TraceCtx(a.tc.trace_id, a.tc.hop + 1, _now_ms())
                done["tc"] = [a.tc.trace_id, a.tc.hop, a.tc.send_ms]
            a.unacked_done = done
            a.unacked_metric = {
                "type": "task_metric_completed", "task_id": tid,
                "peer_id": a.peer_id, "timestamp_ms": _now_ms()}
            a.unacked_tc = a.tc
            self._publish_done(a, now)
            a.task = None
            a.picked = False
            a.tc = None
            a.exec_emitted = False
            self.done_count += 1
            # outcome ledger (ISSUE 11): a second completion of the same
            # id is a DUPLICATED task — the chaos judge's red line
            if tid in self.done_ids:
                self.done_dups += 1
                _reg.count("sim.tasks_done_dup")
            else:
                self.done_ids.add(tid)
            _reg.count("sim.tasks_done")

    # -- inbound ----------------------------------------------------------
    def _on_move(self, d: dict, now: float) -> None:
        a = self.agents.get(d.get("peer_id"))
        if a is None:
            return
        tc = _events.parse_tc(d)
        if tc is not None and a.tc is not None \
                and tc[0] == a.tc.trace_id:
            if tc[1] > a.tc.hop:  # max-merge semantics
                a.tc = pc.TraceCtx(a.tc.trace_id, tc[1], a.tc.send_ms)
            if not a.exec_emitted and a.task is not None:
                # first obeyed instruction: the planning wait has ended
                a.exec_emitted = True
                _events.emit("task.exec", trace_id=tc[0], hop=tc[1],
                             task_id=int(a.task["task_id"]),
                             peer=a.peer_id, send_ms=tc[2])
        a.pos = self._cell(d["next_pos"])
        self.moves += 1
        if self.echo_moves:
            # obey and re-broadcast immediately, like the real agent —
            # this echo IS the position load that saturates the bus
            self._beacon(a)
            a.next_hb = now + self.heartbeat_s
        self._arrival(a, now)

    def _on_task(self, d: dict, now: float) -> None:
        a = self.agents.get(d.get("peer_id"))
        if a is None:
            return
        tid = int(d["task_id"])
        if a.unacked_done is not None \
                and int(a.unacked_done["task_id"]) == tid:
            # the manager re-sent a task we already completed (its done
            # was lost): refuse the duplicate, heal by retransmitting
            self._publish_done(a, now, retransmit=True)
            return
        if a.task is not None and int(a.task["task_id"]) == tid:
            return  # duplicate delivery of the task in progress
        a.task = d
        a.picked = False
        a.exec_emitted = False
        if tid not in self._task_specs_seen:
            # capture evidence (ISSUE 11): first sighting of a task id =
            # its arrival in the window.  The spec event (id + endpoint
            # cells, no trace_id so the flight ring always keeps it)
            # plus the recorder hook make this the single point both
            # capture paths source task traffic from.
            self._task_specs_seen.add(tid)
            try:
                pickup = [int(d["pickup"][0]), int(d["pickup"][1])]
                delivery = [int(d["delivery"][0]), int(d["delivery"][1])]
            except (KeyError, IndexError, TypeError, ValueError):
                pickup = delivery = None
            if pickup is not None:
                _events.emit("task.spec", task_id=tid, pickup=pickup,
                             delivery=delivery)
                if self.capture is not None:
                    self.capture.record_task(tid, pickup, delivery)
        tc = _events.parse_tc(d)
        a.tc = pc.TraceCtx(*tc) if tc is not None else None
        self.adopted += 1
        _reg.count("sim.tasks_adopted")
        if tc is not None:
            _events.emit("task.claim", trace_id=tc[0], hop=tc[1],
                         task_id=tid, peer=a.peer_id, send_ms=tc[2])
        self._beacon(a)
        a.next_hb = now + self.heartbeat_s
        self._arrival(a, now)  # degenerate: already at the delivery

    def _on_msg(self, d: dict, now: float) -> None:
        typ = d.get("type")
        if typ == "move_instruction":
            self._on_move(d, now)
        elif typ == "done_ack":
            a = self.agents.get(d.get("peer_id"))
            if a is not None and a.unacked_done is not None \
                    and int(a.unacked_done["task_id"]) == d.get("task_id"):
                tc = _events.parse_tc(d)
                if tc is not None:
                    _events.emit("task.done_ack", trace_id=tc[0], hop=tc[1],
                                 task_id=int(d["task_id"]), peer=a.peer_id,
                                 send_ms=tc[2])
                a.unacked_done = None
                a.unacked_metric = None
                a.unacked_tc = None
                self.acked += 1
        elif typ == "task_withdrawn":
            a = self.agents.get(d.get("peer_id"))
            if a is not None and a.task is not None \
                    and int(a.task["task_id"]) == d.get("task_id"):
                a.task = None
                a.picked = False
                a.tc = None
                self.withdrawn += 1
        elif typ == "world_update":
            self.world_updates += 1
            _reg.count("sim.world_updates")
            # capture evidence (ISSUE 11): the ACCEPTED toggle list (the
            # manager broadcasts only what it applied) is the replayable
            # world traffic — requests that were rejected never were
            # part of the world the fleet experienced
            toggles = d.get("toggles")
            seq = int(d.get("world_seq") or 0)
            if toggles:
                _events.emit("world.update", seq=seq, toggles=toggles)
                if self.capture is not None:
                    self.capture.record_world(seq, toggles)
        elif typ == "world_update_applied":
            self.world_accepted += int(d.get("accepted") or 0)
            self.world_rejected += len(d.get("rejected") or [])
        elif typ is None and "pickup" in d and "delivery" in d:
            self._on_task(d, now)

    def _audit_entries(self):
        """The pool's agent-side view digest (ISSUE 10): sorted held
        task ids, the SEC_VIEW canon the manager also beacons — their
        digests agree iff the manager's in-flight set and the agents'
        held set are the same tasks."""
        held = [int(a.task["task_id"]) for a in self.agents.values()
                if a.task is not None]
        d, n = _audit.view_digest(held)
        return ([_audit.AuditEntry(_audit.SEC_VIEW, n, 0, 0, d)],
                {"held": n})

    def _audit_beat(self, now: float) -> None:
        if self._audit_beacon is not None \
                and self._audit_beacon.maybe_beat(now) is not None:
            self.audit_beacons += 1

    # -- the loop ---------------------------------------------------------
    def _due(self, now: float) -> None:
        """Heartbeats due this slice + done retransmits past their retry."""
        self._audit_beat(now)
        for a in self.agents.values():
            if now >= a.next_hb:
                self._beacon(a)
                a.next_hb = now + self.heartbeat_s
            if a.unacked_done is not None and now >= a.done_next_retry:
                self._publish_done(a, now, retransmit=True)

    def pump(self, budget_s: float) -> None:
        """Drive the pool for ``budget_s`` seconds: deliver inbound
        traffic, beat due heartbeats, retransmit unacked dones."""
        end = time.monotonic() + budget_s
        while True:
            now = time.monotonic()
            if now >= end:
                return
            self._due(now)
            f = self.bus.recv(timeout=min(0.05, end - now))
            drained = 0
            while f is not None:
                if f.get("op") == "msg":
                    self._on_msg(f.get("data") or {}, time.monotonic())
                drained += 1
                # drain what is buffered before re-checking clocks (at
                # thousands of agents the move stream outpaces a strict
                # one-frame-per-recv loop) — but BOUNDED: a sustained
                # burst must not starve heartbeats/retransmits (_due) or
                # overshoot the caller's budget
                if drained >= 512 or time.monotonic() >= end:
                    break
                f = self.bus.recv(timeout=0.0)

    def heartbeat_all(self) -> None:
        """Force one immediate beacon per agent (pool startup: make the
        whole roster known to the manager before tasks are injected)."""
        now = time.monotonic()
        for k, a in enumerate(self.agents.values()):
            self._beacon(a)
            # re-stagger: the next regular beat keeps the smooth phase
            a.next_hb = now + self.heartbeat_s * (1 + k / max(1, self.n))

    def busy(self) -> int:
        return sum(1 for a in self.agents.values() if a.task is not None)

    def stats(self) -> dict:
        out = {"agents": self.n, "adopted": self.adopted,
               "done": self.done_count, "acked": self.acked,
               "moves": self.moves, "withdrawn": self.withdrawn,
               "busy": self.busy()}
        if self.done_dups:
            out["done_dups"] = self.done_dups
        if self.world_updates or self.world_accepted or self.world_rejected:
            out["world_updates"] = self.world_updates
            out["world_accepted"] = self.world_accepted
            out["world_rejected"] = self.world_rejected
        return out

    def close(self) -> None:
        self.bus.close()
