"""Control-plane HA (ISSUE 15): the ``ledger1`` replication canon,
replica state machine, and lease/election rules.

The manager is the system of record for the task ledger — SIGKILL it
and every open task dies with it.  This module is the Python half of
the fix (native mirror: ``cpp/common/ha.hpp``, byte-identical and
golden-tested via ``codec_golden --ledger-encode/--ledger-decode`` like
``handoff1``):

- **the ``ledger1`` record** — a versioned binary blob (packed1-family
  discipline: little-endian, base64-framed in a bus JSON envelope on
  raw topic ``mapd.ha``) carrying the active manager's task ledger
  (pending + in-flight entries with their assigned agents), its
  dispatch watermarks (plan seq, world epoch, next task id), the
  accumulated world-toggle state, and the active's own **audit-canon
  ledger/view digests over the full post-apply state** — the integrity
  check a replica verifies after every apply, and the equality the
  takeover acceptance is judged on;
- **:class:`LedgerEncoder`** — active-side delta tracking: full
  snapshot first (and every ``snapshot_every``, and on demand via
  ``ha_resync_request``), then deltas carrying only changed/added
  tasks, removed ids, and changed world cells, seq-chained like the
  packed plan wire;
- **:class:`LedgerReplica`** — standby-side mirror: applies the chain,
  raises :class:`HaSeqGapError` on a break (the owner publishes
  ``ha_resync_request`` — the same snapshot-resync discipline as the
  plan wire), resets on a NEWER active incarnation, ignores stale
  incarnations, and verifies the record's digests against its own
  recomputation (``divergences`` counts mismatches; a divergent replica
  must resync, never promote on bad state);
- **:class:`LeaseMonitor`** — the active's liveness lease, judged by
  the auditor's silent-peer rule: quiet past 3 of its own advertised
  intervals plus a 1 s absolute grace = expired;
- **:func:`should_demote`** — the split-brain guard: orderings are
  judged on ``(incarnation, peer_id)``; both sides apply the same rule
  to the same announcements, so exactly ONE of two claimants yields.
  An old-incarnation active that resumes (SIGSTOP/SIGCONT through a
  takeover) hears the promoted standby's higher incarnation and
  demotes instead of dual-dispatching.

``JG_HA`` unset/0 is the default-off kill switch: no process publishes
or subscribes anything on ``mapd.ha`` and the single-manager wire is
byte-identical (raw-socket pin test in tests/test_ha.py).
"""

from __future__ import annotations

import base64
import os
import struct
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from p2p_distributed_tswap_tpu.obs import audit as _audit

HA_TOPIC = "mapd.ha"
KILL_ENV = "JG_HA"
LEASE_MS_ENV = "JG_HA_LEASE_MS"
DEFAULT_LEASE_MS = 500
# the takeover sweep-hold (one claim window): a promoted standby waits
# this long for in-flight tasks' agents to report before re-queueing —
# the task_resend_ms analog of PR 4's post-outage hold
DEFAULT_HOLD_MS = 5000
SNAPSHOT_EVERY = 64

LEDGER_MAGIC = 0x3147444C  # b"LDG1" little-endian
LEDGER_VERSION = 1
FLAG_SNAPSHOT = 1

# task states (the audit ledger canon's state byte — never renumber)
TASK_PENDING = _audit.TASK_PENDING
TASK_TO_PICKUP = _audit.TASK_TO_PICKUP
TASK_TO_DELIVERY = _audit.TASK_TO_DELIVERY

_HEAD = struct.Struct("<IBBHIIII")       # magic, ver, flags, reserved,
                                         # n_tasks, n_removed, n_world,
                                         # n_handoffs (u32 counts: a
                                         # production-scale ledger must
                                         # never truncate silently)
_WATERMARKS = struct.Struct("<qqqqqqQQ")  # seq, base_seq, incarnation,
                                          # plan_seq, world_seq,
                                          # next_task_id, ledger_digest,
                                          # view_digest
_TASK_FIXED = struct.Struct("<qBiiH")     # id, state, pickup, delivery,
                                          # peer_len
_REMOVED = struct.Struct("<q")
_WORLD = struct.Struct("<iB")
_HANDOFF_FIXED = struct.Struct("<iqqiiBBqiiH")  # dst, seq, epoch, pos,
                                                # goal, phase, has_task,
                                                # task_id, pickup,
                                                # delivery, peer_len


def enabled() -> bool:
    """HA is OFF unless JG_HA is set truthy — the default keeps the
    single-manager wire byte-identical (no mapd.ha frames at all)."""
    return os.environ.get(KILL_ENV, "") not in ("", "0")


def lease_ms() -> int:
    try:
        return int(os.environ.get(LEASE_MS_ENV, "") or DEFAULT_LEASE_MS)
    except ValueError:
        return DEFAULT_LEASE_MS


class HaCodecError(ValueError):
    """Malformed ledger1 blob (bad magic/version/lengths)."""


class HaSeqGapError(RuntimeError):
    """A delta arrived whose base_seq is not the replica's last applied
    seq: a record was lost.  Owner must publish ``ha_resync_request``."""

    def __init__(self, have_seq: int, base_seq: int):
        super().__init__(f"ledger delta base_seq {base_seq} != last "
                         f"applied {have_seq}")
        self.have_seq = have_seq
        self.base_seq = base_seq


@dataclass(frozen=True)
class LedgerTask:
    """One replicated ledger entry.  ``peer`` is the assigned agent for
    in-flight entries (state 1/2), empty for pending ones."""
    task_id: int
    state: int
    pickup: int
    delivery: int
    peer: str = ""


@dataclass(frozen=True)
class HandoffOut:
    """One UNACKED outbound cross-region handoff (the sender's outbox
    entry, ISSUE 14's retransmit-until-ack record).  Replicated so a
    promoted standby RESUMES the retransmit instead of losing a task
    that was mid-transfer when the active died: the entry carries
    everything needed to rebuild the exact original ``handoff1`` frame
    (same seq + sender epoch, so the receiver's dedup guard keeps
    working — an already-applied record re-acks, a lost one applies)."""
    dst: int
    seq: int
    epoch: int
    peer: str
    pos: int
    goal: int
    phase: int = 0
    task_id: Optional[int] = None
    pickup: int = 0
    delivery: int = 0


@dataclass
class LedgerRec:
    """One replication record.  ``seq`` chains per active incarnation;
    ``base_seq`` is 0 for snapshots, else the previous record's seq.
    ``ledger_digest``/``view_digest`` are the ACTIVE's audit-canon
    digests over its FULL post-record ledger (not just the delta) — the
    replica recomputes and compares after every apply."""
    seq: int
    base_seq: int
    incarnation: int
    plan_seq: int
    world_seq: int
    next_task_id: int
    snapshot: bool
    tasks: List[LedgerTask] = field(default_factory=list)
    removed: List[int] = field(default_factory=list)
    world: List[Tuple[int, int]] = field(default_factory=list)
    # the sender's FULL unacked handoff outbox (not a diff: it is tiny
    # and short-lived, so every record that ships replaces the
    # replica's view wholesale)
    handoffs: List[HandoffOut] = field(default_factory=list)
    ledger_digest: int = 0
    view_digest: int = 0


def ledger_view_digests(tasks: Iterable[LedgerTask]) -> Tuple[int, int,
                                                              int, int]:
    """``(ledger_digest, ledger_count, view_digest, view_count)`` over a
    full ledger, using the audit canon (obs/audit.py) — the standby's
    replica hashes equal to the active's beaconed digests iff they hold
    the same ledger."""
    tup = [(t.task_id, t.state, t.pickup, t.delivery) for t in tasks]
    ld, lc = _audit.ledger_digest(tup)
    vd, vc = _audit.view_digest(
        [tid for tid, st, _, _ in tup if st != TASK_PENDING])
    return ld, lc, vd, vc


def encode_ledger(rec: LedgerRec) -> bytes:
    if not (0 <= len(rec.tasks) < 1 << 32
            and 0 <= len(rec.removed) < 1 << 32
            and 0 <= len(rec.world) < 1 << 32
            and 0 <= len(rec.handoffs) < 1 << 32):
        raise HaCodecError("ledger1 section too large")
    out = bytearray(_HEAD.pack(
        LEDGER_MAGIC, LEDGER_VERSION,
        FLAG_SNAPSHOT if rec.snapshot else 0, 0,
        len(rec.tasks), len(rec.removed), len(rec.world),
        len(rec.handoffs)))
    out += _WATERMARKS.pack(rec.seq, rec.base_seq, rec.incarnation,
                            rec.plan_seq, rec.world_seq,
                            rec.next_task_id,
                            rec.ledger_digest & ((1 << 64) - 1),
                            rec.view_digest & ((1 << 64) - 1))
    for t in rec.tasks:
        peer = t.peer.encode()
        if len(peer) >= 65536:
            raise HaCodecError("ledger1 peer id too long")
        out += _TASK_FIXED.pack(int(t.task_id), int(t.state) & 0xFF,
                                int(t.pickup), int(t.delivery), len(peer))
        out += peer
    for tid in rec.removed:
        out += _REMOVED.pack(int(tid))
    for cell, blocked in rec.world:
        out += _WORLD.pack(int(cell), 1 if blocked else 0)
    for h in rec.handoffs:
        peer = h.peer.encode()
        if len(peer) >= 65536:
            raise HaCodecError("ledger1 peer id too long")
        out += _HANDOFF_FIXED.pack(
            int(h.dst), int(h.seq), int(h.epoch), int(h.pos),
            int(h.goal), int(h.phase) & 0xFF,
            1 if h.task_id is not None else 0,
            int(h.task_id or 0), int(h.pickup), int(h.delivery),
            len(peer))
        out += peer
    return bytes(out)


def decode_ledger(buf: bytes) -> LedgerRec:
    if len(buf) < _HEAD.size + _WATERMARKS.size:
        raise HaCodecError("short ledger1 blob")
    magic, version, flags, _, n_tasks, n_removed, n_world, n_handoffs = \
        _HEAD.unpack_from(buf, 0)
    if magic != LEDGER_MAGIC:
        raise HaCodecError(f"bad ledger1 magic 0x{magic:08x}")
    if version != LEDGER_VERSION:
        raise HaCodecError(f"unsupported ledger1 version {version}")
    (seq, base_seq, incarnation, plan_seq, world_seq, next_task_id,
     ledger_digest, view_digest) = _WATERMARKS.unpack_from(buf, _HEAD.size)
    off = _HEAD.size + _WATERMARKS.size
    tasks: List[LedgerTask] = []
    for _ in range(n_tasks):
        if off + _TASK_FIXED.size > len(buf):
            raise HaCodecError("truncated ledger1 task section")
        tid, state, pickup, delivery, peer_len = \
            _TASK_FIXED.unpack_from(buf, off)
        off += _TASK_FIXED.size
        if off + peer_len > len(buf):
            raise HaCodecError("truncated ledger1 peer id")
        peer = buf[off:off + peer_len].decode()
        off += peer_len
        if state not in (TASK_PENDING, TASK_TO_PICKUP, TASK_TO_DELIVERY):
            raise HaCodecError(f"bad ledger1 task state {state}")
        tasks.append(LedgerTask(tid, state, pickup, delivery, peer))
    if off + n_removed * _REMOVED.size + n_world * _WORLD.size > len(buf):
        raise HaCodecError("truncated ledger1 removed/world sections")
    removed = [_REMOVED.unpack_from(buf, off + k * _REMOVED.size)[0]
               for k in range(n_removed)]
    off += n_removed * _REMOVED.size
    world = []
    for k in range(n_world):
        cell, blocked = _WORLD.unpack_from(buf, off + k * _WORLD.size)
        world.append((cell, int(blocked)))
    off += n_world * _WORLD.size
    handoffs: List[HandoffOut] = []
    for _ in range(n_handoffs):
        if off + _HANDOFF_FIXED.size > len(buf):
            raise HaCodecError("truncated ledger1 handoff section")
        (dst, hseq, epoch, pos, goal, phase, has_task, task_id, pickup,
         delivery, peer_len) = _HANDOFF_FIXED.unpack_from(buf, off)
        off += _HANDOFF_FIXED.size
        if off + peer_len > len(buf):
            raise HaCodecError("truncated ledger1 handoff peer id")
        peer = buf[off:off + peer_len].decode()
        off += peer_len
        handoffs.append(HandoffOut(
            dst, hseq, epoch, peer, pos, goal, phase,
            task_id if has_task else None, pickup, delivery))
    if len(buf) != off:
        raise HaCodecError(f"ledger1 length {len(buf)} != expected {off}")
    return LedgerRec(seq=seq, base_seq=base_seq, incarnation=incarnation,
                     plan_seq=plan_seq, world_seq=world_seq,
                     next_task_id=next_task_id,
                     snapshot=bool(flags & FLAG_SNAPSHOT), tasks=tasks,
                     removed=removed, world=world, handoffs=handoffs,
                     ledger_digest=ledger_digest, view_digest=view_digest)


def encode_ledger_b64(rec: LedgerRec) -> str:
    return base64.b64encode(encode_ledger(rec)).decode()


def decode_ledger_b64(data: str) -> LedgerRec:
    try:
        raw = base64.b64decode(data, validate=True)
    except Exception as e:
        raise HaCodecError(f"bad ledger1 base64: {e}") from None
    return decode_ledger(raw)


class LedgerEncoder:
    """Active-side delta tracking, mirrored natively in
    cpp/common/ha.hpp LedgerEncoder.  Determinism contract (golden-
    tested like PackedFleetEncoder): removed ids scan the shadow in
    ascending task-id order; changed/added tasks follow the CALLER's
    ledger order; world diffs are emitted sorted by cell ascending; a
    snapshot ships the full ledger in caller order plus the full world
    state sorted by cell, and resets the chain."""

    def __init__(self, incarnation: int,
                 snapshot_every: int = SNAPSHOT_EVERY):
        self.incarnation = incarnation
        self.snapshot_every = snapshot_every
        self.shadow: Dict[int, LedgerTask] = {}
        self.world_shadow: Dict[int, int] = {}
        self.handoff_shadow: List[HandoffOut] = []
        self.last_seq = 0
        self.since_snapshot = 0
        self.force_snapshot = True

    def request_snapshot(self) -> None:
        self.force_snapshot = True

    def encode_tick(self, plan_seq: int, world_seq: int,
                    next_task_id: int, tasks: Iterable[LedgerTask],
                    world: Optional[Dict[int, int]] = None,
                    handoffs: Optional[Iterable[HandoffOut]] = None
                    ) -> Optional[LedgerRec]:
        """One replication beat.  Returns None when nothing changed (and
        no snapshot is due) — liveness rides the separate ``ha_lease``
        frame, not empty records.  ``handoffs`` is the sender's FULL
        unacked outbox, shipped wholesale in every emitted record (and
        its change alone also triggers one)."""
        tasks = list(tasks)
        world = dict(world or {})
        handoffs = sorted(handoffs or [], key=lambda h: (h.dst, h.seq))
        ld, _, vd, _ = ledger_view_digests(tasks)
        snapshot = (self.force_snapshot
                    or self.since_snapshot + 1 >= self.snapshot_every)
        if snapshot:
            rec = LedgerRec(
                seq=self.last_seq + 1, base_seq=0,
                incarnation=self.incarnation, plan_seq=plan_seq,
                world_seq=world_seq, next_task_id=next_task_id,
                snapshot=True, tasks=tasks, removed=[],
                world=sorted(world.items()), handoffs=handoffs,
                ledger_digest=ld, view_digest=vd)
            self.shadow = {t.task_id: t for t in tasks}
            self.world_shadow = world
            self.handoff_shadow = handoffs
            self.last_seq = rec.seq
            self.since_snapshot = 0
            self.force_snapshot = False
            return rec
        current = {t.task_id for t in tasks}
        removed = sorted(tid for tid in self.shadow if tid not in current)
        changed = [t for t in tasks if self.shadow.get(t.task_id) != t]
        world_diff = sorted((c, b) for c, b in world.items()
                            if self.world_shadow.get(c) != b)
        if not removed and not changed and not world_diff \
                and handoffs == self.handoff_shadow:
            return None
        rec = LedgerRec(
            seq=self.last_seq + 1, base_seq=self.last_seq,
            incarnation=self.incarnation, plan_seq=plan_seq,
            world_seq=world_seq, next_task_id=next_task_id,
            snapshot=False, tasks=changed, removed=removed,
            world=world_diff, handoffs=handoffs,
            ledger_digest=ld, view_digest=vd)
        for tid in removed:
            del self.shadow[tid]
        for t in changed:
            self.shadow[t.task_id] = t
        for c, b in world_diff:
            self.world_shadow[c] = b
        self.handoff_shadow = handoffs
        self.last_seq = rec.seq
        self.since_snapshot += 1
        return rec


class LedgerReplica:
    """Standby-side mirror of the active's ledger.  ``apply`` enforces
    the chain (gap -> :class:`HaSeqGapError`; the owner publishes
    ``ha_resync_request`` and the active answers with a snapshot — the
    plan wire's snapshot-resync path, reused), handles incarnation
    moves (newer active: reset and demand a snapshot; older: drop), and
    verifies the record's full-ledger digests against its own
    recomputation."""

    def __init__(self):
        self.tasks: Dict[int, LedgerTask] = {}
        self.world: Dict[int, int] = {}
        # the active's unacked handoff outbox as last shipped — a
        # promoted standby resumes retransmitting exactly these
        self.handoffs: List[HandoffOut] = []
        self.seq = 0
        self.incarnation = 0
        self.plan_seq = 0
        self.world_seq = 0
        self.next_task_id = 0
        self.applied = 0
        self.divergences = 0
        self.stale_dropped = 0

    def apply(self, rec: LedgerRec) -> bool:
        """Apply one record.  True = applied and digest-verified; False
        = applied but the recomputed digests disagreed with the record's
        (the replica must resync, never promote on this state).  Raises
        :class:`HaSeqGapError` on a chain break (including a NEW
        incarnation opening with a delta)."""
        if self.incarnation and rec.incarnation < self.incarnation:
            # a delayed frame from a dead incarnation: never apply
            self.stale_dropped += 1
            return True
        if rec.incarnation > self.incarnation:
            # the active restarted (or a standby promoted): its chain
            # starts over — a delta against the OLD chain is a gap
            self.tasks.clear()
            self.world.clear()
            self.handoffs = []
            self.seq = 0
            self.incarnation = rec.incarnation
            if not rec.snapshot:
                raise HaSeqGapError(0, rec.base_seq)
        if rec.snapshot:
            self.tasks = {t.task_id: t for t in rec.tasks}
            self.world = dict(rec.world)
        else:
            if rec.base_seq != self.seq:
                raise HaSeqGapError(self.seq, rec.base_seq)
            for tid in rec.removed:
                self.tasks.pop(tid, None)
            for t in rec.tasks:
                self.tasks[t.task_id] = t
            for cell, blocked in rec.world:
                self.world[cell] = blocked
        self.handoffs = list(rec.handoffs)  # wholesale, every record
        self.seq = rec.seq
        self.plan_seq = rec.plan_seq
        self.world_seq = rec.world_seq
        self.next_task_id = rec.next_task_id
        self.applied += 1
        ld, _, vd, _ = ledger_view_digests(self.tasks.values())
        ok = (ld == rec.ledger_digest and vd == rec.view_digest)
        if not ok:
            self.divergences += 1
        return ok

    def digests(self) -> dict:
        """The replica's audit-canon digests — what the promoted
        standby announces at the takeover watermark."""
        ld, lc, vd, vc = ledger_view_digests(self.tasks.values())
        return {"ledger": _audit.digest_hex(ld), "ledger_count": lc,
                "view": _audit.digest_hex(vd), "view_count": vc,
                "seq": self.seq, "plan_seq": self.plan_seq,
                "world_seq": self.world_seq}

    def inflight(self) -> List[LedgerTask]:
        return [t for t in self.tasks.values()
                if t.state != TASK_PENDING]

    def pending(self) -> List[LedgerTask]:
        return [t for t in self.tasks.values()
                if t.state == TASK_PENDING]


class LeaseMonitor:
    """The standby's view of the active's liveness lease — the
    auditor's silent-peer rule (obs/audit.py): quiet past 3 of the
    active's own advertised intervals plus a 1 s absolute grace."""

    def __init__(self):
        self.peer = ""
        self.incarnation = 0
        self.interval_ms = DEFAULT_LEASE_MS
        self.last_ms = 0
        self.repl_seq = 0

    def note(self, peer: str, incarnation: int, now_ms: int,
             interval_ms: Optional[int] = None,
             repl_seq: Optional[int] = None) -> None:
        """Any authenticated-enough sign of life from the active (a
        lease frame or a ledger1 record) renews the lease.  A LOWER
        incarnation than the freshest seen never renews — a zombie's
        heartbeats must not keep a standby from promoting past it."""
        if self.incarnation and incarnation < self.incarnation:
            return
        self.peer = peer
        self.incarnation = incarnation
        self.last_ms = now_ms
        if interval_ms:
            self.interval_ms = int(interval_ms)
        if repl_seq is not None:
            self.repl_seq = int(repl_seq)

    def expired(self, now_ms: int) -> bool:
        """True once the active has been silent past the rule.  Never
        expires before the first sign of life — promotion from cold
        start is the caller's (longer) grace, not a lease expiry."""
        if not self.last_ms:
            return False
        return now_ms - self.last_ms > 3 * self.interval_ms + 1000


def takeover_digests_equal(rec: dict) -> Optional[bool]:
    """The one rule every judge of an ``ha_takeover`` frame applies:
    True iff the promoted standby's self-computed ledger/view digests
    equal the failed active's last shipped ones.  None when the frame
    carries NO active digests at all (a cold-start takeover — nothing
    was ever shipped, so there is nothing to compare; rendering that as
    'differ' would invent a replica divergence)."""
    if not rec.get("active_ledger_digest"):
        return None
    return (rec.get("ledger_digest") == rec.get("active_ledger_digest")
            and rec.get("view_digest") == rec.get("active_view_digest"))


def should_demote(my_incarnation: int, my_peer: str,
                  other_incarnation: int, other_peer: str) -> bool:
    """The split-brain guard: between two claimants of one active role,
    the LOWER ``(incarnation, peer_id)`` demotes.  Both sides apply the
    same rule to the same announcements, so exactly one yields — an
    old-incarnation active resuming after a takeover always loses to
    the promoted standby's bumped incarnation."""
    return (other_incarnation, other_peer) > (my_incarnation, my_peer)
