"""Shared busd shard-pool spawner (ISSUE 6 satellite).

Before the pool existed, every harness and test that needed a bus
duplicated the same setup: pick a port, Popen ``mapd_bus``, sleep, hope.
This module is the single place that knows how to launch ONE hub or a
FEDERATED POOL of them — free-port allocation, per-shard log files, the
``--shard/--shards/--peers`` peering flags, and the environment
(``JG_BUS_SHARD_PORTS``) that makes every BusClient in the fleet
shard-aware.  Used by runtime/fleet.py, analysis/bus_scaling.py,
scripts/bus_smoke.py, and the shard-plane tests.

``num_shards=1`` spawns exactly the pre-pool single hub (no peering
flags, no pool env) — the ``JG_BUS_SHARDS=1`` kill switch end to end.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from pathlib import Path
from typing import Callable, List, Optional, Sequence

from p2p_distributed_tswap_tpu.runtime import shmlane

SHARD_PORTS_ENV = "JG_BUS_SHARD_PORTS"


def parse_cpu_affinity(spec) -> Optional[List[int]]:
    """A ``--cpu-affinity`` spec -> ordered CPU id list: "0,1,2" pins
    shard i to cpu ``list[i % len]``; "auto" spreads across every CPU
    this process may use; None/'' disables pinning."""
    if spec is None or spec == "":
        return None
    if spec == "auto":
        if not hasattr(os, "sched_getaffinity"):  # non-Linux: no pinning
            return None
        return sorted(os.sched_getaffinity(0))
    cpus = [int(c) for c in str(spec).split(",") if str(c).strip()]
    if not cpus:
        raise ValueError(f"empty cpu affinity spec: {spec!r}")
    return cpus


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def shard_args(shard: int, num_shards: int, ports: Sequence[int]
               ) -> List[str]:
    """The busd CLI flags that make shard ``shard`` a pool member (empty
    for a single hub, keeping its invocation byte-identical)."""
    if num_shards <= 1:
        return []
    return ["--shard", str(shard), "--shards", str(num_shards),
            "--peers", ",".join(str(p) for p in ports)]


def pool_ports(num_shards: int, home_port: Optional[int] = None
               ) -> List[int]:
    """Allocate the pool's port list: the home shard keeps ``home_port``
    (the fleet's advertised bus port) when given, the rest are free
    ports."""
    ports = [free_port() for _ in range(num_shards)]
    if home_port is not None:
        ports[0] = home_port
    return ports


def pool_env(ports: Sequence[int]) -> dict:
    """Environment that makes every BusClient shard-aware.  Empty for a
    single hub: a one-port pool must keep the legacy wire byte-identical
    (shardmap treats the absent env as 'single hub')."""
    if len(ports) <= 1:
        return {}
    return {SHARD_PORTS_ENV: ",".join(str(p) for p in ports)}


class BusPool:
    """A spawned busd pool (single hub when ``num_shards=1``).

    ``spawn`` customizes process creation — the fleet runner passes its
    own (log capture + exit-code tracking); the default writes per-shard
    logs under ``log_dir`` (or discards output).  Shard 0 is the HOME
    shard: spawned first so higher shards' peering dials succeed on the
    first attempt.
    """

    def __init__(self, binary, num_shards: int = 1,
                 home_port: Optional[int] = None,
                 log_dir: Optional[Path] = None,
                 extra_args: Optional[Sequence[str]] = None,
                 spawn: Optional[Callable] = None,
                 settle_s: float = 0.3,
                 cpu_affinity=None):
        self.num_shards = num_shards
        self.ports = pool_ports(num_shards, home_port)
        self.procs: List[subprocess.Popen] = []
        self._logs: List = []
        # per-shard CPU pinning (ROADMAP item 1 remaining headroom): on a
        # many-core host the pool's shards contend less when each relay
        # loop owns a core.  Spec: "0,1,2" (shard i -> cpu[i % len]),
        # "auto" (spread over this process's allowed CPUs), None = off.
        self.cpu_affinity = parse_cpu_affinity(cpu_affinity)
        # zero-copy lanes (ISSUE 18): lane files of clients that died by
        # SIGKILL survive their sessions; sweep the lane dir once per
        # pool spawn so a fresh fleet never trips over a dead pid's ring
        if shmlane.shm_enabled():
            try:
                shmlane.reclaim_stale()
            except OSError:
                pass  # best-effort hygiene: a locked dir must not block
        for i, port in enumerate(self.ports):
            cmd = [str(binary), str(port),
                   *shard_args(i, num_shards, self.ports),
                   *(extra_args or [])]
            name = "bus" if num_shards <= 1 else f"bus_s{i}"
            if spawn is not None:
                proc = spawn(name, cmd)
            elif log_dir is not None:
                log_dir = Path(log_dir)
                log_dir.mkdir(parents=True, exist_ok=True)
                out = open(log_dir / f"{name}.log", "w")
                self._logs.append(out)
                proc = subprocess.Popen(cmd, stdout=out,
                                        stderr=subprocess.STDOUT)
            else:
                proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                        stderr=subprocess.DEVNULL)
            if self.cpu_affinity and hasattr(os, "sched_setaffinity"):
                # post-spawn pinning is sufficient: busd is a single
                # poll loop (no threads inherit a pre-pin mask)
                cpu = self.cpu_affinity[i % len(self.cpu_affinity)]
                try:
                    os.sched_setaffinity(proc.pid, {cpu})
                except OSError as e:  # bad cpu id / cgroup restriction
                    print(f"⚠️  buspool: cannot pin shard {i} to cpu "
                          f"{cpu}: {e}")
            self.procs.append(proc)
        time.sleep(settle_s)

    @property
    def home_port(self) -> int:
        return self.ports[0]

    def env(self) -> dict:
        return pool_env(self.ports)

    def kill_shard(self, shard: int) -> None:
        """Hard-kill one pool member (the degradation drills: a dead
        shard must cost its regions, not the fleet)."""
        self.procs[shard].kill()
        self.procs[shard].wait(timeout=5)

    def alive(self) -> List[bool]:
        return [p.poll() is None for p in self.procs]

    def close(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
