"""Python client for the host-runtime message bus (cpp/busd).

Speaks the same line-framed JSON protocol as the C++ BusClient
(cpp/common/bus.hpp); used by the solver daemon, the process-spawn test
runner, and integration tests.

Relay fast framing (ISSUE 4, caps-negotiated): the client advertises
``caps:["relay1"]`` in hello; once the hub's welcome echoes the cap,
publishes switch to topic-prefix lines the hub relays without JSON
parsing (``P<topic> <payload>``), and deliveries may arrive as
``M<topic> <from> <payload>`` — :meth:`recv` normalizes those to the
same ``{"op":"msg","topic","from","data"}`` dict, so consumers are
agnostic.  ``JG_BUS_FASTFRAME=0`` (or ``fastframe=False``) pins the
client to the legacy JSON wire; against an old hub (welcome without
caps) it stays legacy automatically.  A topic ending in ``.*``
subscribes by prefix (busd wildcard matching — managers use
``mapd.pos.*`` for the region-sharded position gossip).

Sharded bus pool (ISSUE 6): when the environment advertises a pool
(``JG_BUS_SHARD_PORTS=7450,7451,...`` — or ``shard_ports=[...]`` is
passed), the client becomes SHARD-AWARE: it holds one connection per
shard it needs, routes every subscription and publish to the owning
shard (runtime/shardmap.py — region position topics spread across the
pool, the control plane lives on the home shard), advertises the
``shard1`` cap so busd can suppress duplicate peer-forwarded
deliveries, and reconnects/fails over PER SHARD — a dead shard degrades
its regions, not the fleet.  With a single port (the default and the
``JG_BUS_SHARDS=1`` kill switch) the wire is byte-identical to the
pre-pool client.

Tenant namespace (ISSUE 8): with ``JG_BUS_NS=<tenant>`` (or a
``namespace=`` arg) every logical topic is prefixed ``<tenant>:`` on
the wire and stripped on delivery (runtime/busns.py), so whole fleets
share one busd pool without cross-talk while their role code stays
tenant-agnostic; the hello advertises ``caps:["ns1"]``.  Cross-tenant
infrastructure (solverd serving many fleets) passes ``raw=True`` to
``subscribe``/``publish`` to address wire topics directly.  With no
namespace the wire is byte-identical to the pre-namespace client.

Like the C++ client, it can survive a bus restart: with ``reconnect=True``
a dropped connection is retried with exponential backoff (0.25 s .. 4 s);
on success the client re-sends hello, re-subscribes every topic, and calls
``on_reconnect``.  While disconnected, ``publish`` drops (the bus is a
lossy broadcast medium) and ``recv`` behaves like a timeout.  Every such
drop is now counted (``bus.pub_dropped_disconnected``), and CONTROL-PLANE
frames (anything busd itself would refuse to shed: not position beacons,
not metrics, not path samples) go to a small bounded replay outbox that
is flushed when the owning shard's connection comes back — so a manager
command published into a bus bounce is delayed, not lost.  Non-home
shards always self-heal with the same backoff, independent of the
``reconnect`` flag.  The reference's brokerless gossipsub mesh has no hub
to lose — with this, losing busd degrades the fleet instead of
destroying it (VERDICT r2 item 5).

Zero-copy same-host lanes (ISSUE 18, caps ``shm1``): with ``JG_BUS_SHM``
set truthy (or ``shm=True``) the client creates one shared-memory ring
pair per shard link (runtime/shmlane.py ≡ cpp/common/shmlane.hpp) and
offers it in hello (``"shm": {"path": ..., "v": 1}``); once the hub's
welcome echoes ``shm1``, droppable-class frames (beacons/metrics/path)
move through the rings as the exact relay lines — publishes via the c2s
ring, deliveries via the s2c ring — while TCP keeps the control plane,
oversized frames, and cross-host links.  Ring overflow falls back to TCP
per frame (``bus.shm_fallbacks`` — never a stall); the lane's lifetime is
the TCP session (torn down + unlinked on disconnect, rebuilt on
reconnect).  ``JG_BUS_SHM`` unset keeps the wire byte-identical (pinned
by tests/test_shmlane.py).

Beacon aggregation (ISSUE 18, caps ``agg1``): with ``JG_BUS_AGG_MS>0``
the client advertises ``agg1`` and the hub may deliver one coalesced
multi-agent frame per region topic per window; :meth:`recv` transparently
explodes it back into per-peer ``pos1`` message dicts, so consumers never
see the aggregate.

Network accounting lives in the unified live-metrics registry
(obs/registry.py): per-topic ``bus.msgs_sent`` / ``bus.bytes_sent`` /
``bus.msgs_received`` / ``bus.bytes_received`` counters, counting ACTUAL
wire bytes (the framed line including its newline — the pre-registry
NetworkMetrics counted the unframed line, so py and cpp bandwidth numbers
disagreed by one byte per message).  ``registry.network_summary()`` is the
rolled-up view; the ``mapd.metrics`` beacon ships the raw counters.
"""

from __future__ import annotations

import base64
import json
import os
import select
import socket
import time
from collections import deque
from typing import Callable, Iterator, List, Optional

from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.obs import trace
from p2p_distributed_tswap_tpu.runtime import busns, shardmap, shmlane
from p2p_distributed_tswap_tpu.runtime import plan_codec

# Topics busd's slow-consumer policy may shed (droppable streams) — the
# complement is the control plane the replay outbox preserves.  Judged
# on the LOGICAL topic: a tenant's beacons shed like anyone else's.
_DROPPABLE_PREFIX = "mapd.pos."
_DROPPABLE_TOPICS = ("mapd.metrics", "mapd.path")


def _is_control_topic(topic: str) -> bool:
    topic = busns.strip_ns(topic)
    return not (topic.startswith(_DROPPABLE_PREFIX)
                or topic in _DROPPABLE_TOPICS)


class _Link:
    """One shard connection: socket + framing buffer + per-link caps and
    backoff state (each shard negotiates and fails independently)."""

    __slots__ = ("shard", "port", "sock", "buf", "topics", "backoff",
                 "next_attempt", "attempted", "fast_hub", "hub_caps",
                 "lane", "shm_live")

    def __init__(self, shard: int, port: int):
        self.shard = shard
        self.port = port
        self.sock: Optional[socket.socket] = None
        self.buf = b""
        self.topics: set[str] = set()  # subscriptions owned by this shard
        self.backoff = 0.0
        self.next_attempt = 0.0
        self.attempted = False  # ever dialed (lazy links dial on demand)
        self.fast_hub = False
        self.hub_caps: Optional[list] = None
        self.lane: Optional[shmlane.ShmLane] = None  # offered ring pair
        self.shm_live = False  # hub's welcome echoed shm1: lane is on


class BusClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7400,
                 peer_id: Optional[str] = None, timeout: float = 5.0,
                 reconnect: bool = False,
                 on_reconnect: Optional[Callable[[], None]] = None,
                 registry: Optional[_reg.Registry] = None,
                 fastframe: Optional[bool] = None,
                 shard_ports: Optional[List[int]] = None,
                 namespace: Optional[str] = None,
                 shm: Optional[bool] = None):
        self.peer_id = peer_id or f"py-{int(time.time() * 1000) % 10 ** 10}"
        self._host, self._timeout = host, timeout
        self._reconnect = reconnect
        self._on_reconnect = on_reconnect
        # tenant namespace: explicit arg beats JG_BUS_NS beats none
        self._ns = (busns.validate(namespace) if namespace is not None
                    else busns.namespace_from_env())
        self._ns_prefix = busns.wire_topic(self._ns, "") if self._ns else ""
        # relay fast framing: advertised in hello, armed by the hub's
        # welcome (see module docstring); None = the JG_BUS_FASTFRAME env
        self._fastframe = (os.environ.get("JG_BUS_FASTFRAME", "1")
                           not in ("0", "false", "")
                           if fastframe is None else fastframe)
        # shm lanes (ISSUE 18) are OPT-IN: offered only when JG_BUS_SHM
        # is truthy (or shm=True); they ride the relay framing, so
        # JG_BUS_FASTFRAME=0 vetoes them too
        self._shm = (shmlane.shm_enabled() if shm is None
                     else bool(shm)) and self._fastframe
        # beacon-aggregation window: >0 advertises the agg1 cap (we can
        # decode coalesced region beacons); 0/unset = legacy singles
        self._agg_ms = int(os.environ.get("JG_BUS_AGG_MS", "0") or 0)
        # frames ready ahead of the TCP buffers: lane deliveries and
        # exploded agg1 entries queue here for recv()/_next_buffered
        self._pending: deque = deque()
        # shard pool map: explicit arg beats JG_BUS_SHARD_PORTS beats the
        # single `port` (the legacy single-hub wire, byte-identical)
        ports = (list(shard_ports) if shard_ports
                 else shardmap.shard_ports_from_env(port))
        self._links = [_Link(i, p) for i, p in enumerate(ports)]
        self._n = len(self._links)
        self._rr = 0  # round-robin cursor for buffered-frame draining
        # bounded control-plane replay outbox: (topic, payload) of frames
        # publish() had to drop while the owning shard was down, flushed
        # in arrival order when that shard's link reconnects.
        # JG_BUS_OUTBOX=0 disables replay entirely (same as the C++
        # client — never an unbounded queue)
        self._outbox_max = int(os.environ.get("JG_BUS_OUTBOX", "128")
                               or 128)
        self._outbox: deque = deque(maxlen=max(1, self._outbox_max))
        # network accounting sink: the process registry unless a test
        # injects its own (obs/registry.py is the single source of truth)
        self.registry = registry or _reg.get_registry()
        self._closed = False
        # initial connect to the HOME shard still raises: startup contract
        self._connect(self._links[shardmap.HOME_SHARD])

    # -- back-compat views (home-shard semantics) -------------------------
    @property
    def port(self) -> int:
        return self._links[shardmap.HOME_SHARD].port

    @property
    def sock(self):
        return self._links[shardmap.HOME_SHARD].sock

    @property
    def hub_caps(self) -> Optional[list]:
        return self._links[shardmap.HOME_SHARD].hub_caps

    @property
    def fast_hub(self) -> bool:
        """True once the hub's welcome negotiated the relay1 framing."""
        return self._links[shardmap.HOME_SHARD].fast_hub

    @property
    def connected(self) -> bool:
        return self._links[shardmap.HOME_SHARD].sock is not None

    @property
    def num_shards(self) -> int:
        return self._n

    # -- connection management -------------------------------------------
    def _connect(self, link: _Link,
                 dial_timeout: Optional[float] = None) -> None:
        """Dial one shard.  ``dial_timeout`` bounds the CONNECT only —
        reconnect/lazy dials inside a role loop must not block for the
        full I/O timeout against a SYN-dropping dead host (the C++
        client bounds the same dial to 250 ms–1 s)."""
        link.attempted = True
        link.sock = socket.create_connection(
            (self._host, link.port),
            timeout=self._timeout if dial_timeout is None else dial_timeout)
        link.sock.settimeout(self._timeout)
        link.buf = b""
        link.backoff = 0.0
        link.fast_hub = False  # renegotiated by the hub's welcome
        hello = {"op": "hello", "peer_id": self.peer_id}
        caps = (["relay1"] if self._fastframe else [])
        # shard1 is orthogonal to the relay framing: a pool client must
        # advertise it even with JG_BUS_FASTFRAME=0, or busd would count
        # its span wildcards as peering interest and double-deliver.  It
        # rides only on a real pool — the single-hub hello (and the
        # JG_BUS_SHARDS=1 kill switch) stays byte-identical.
        if self._n > 1:
            caps.append("shard1")
        if self._ns:
            # namespaced tenant client (ISSUE 8); absent = legacy wire
            caps.append("ns1")
        # shm lane offer (ISSUE 18): create the ring pair BEFORE the
        # hello so the hub can attach on receipt; frames ride it only
        # after the welcome echoes shm1.  A same-name leftover (stale
        # after a SIGKILL) is reclaimed by create_lane.
        self._teardown_lane(link)
        if self._shm:
            try:
                link.lane = shmlane.create_lane(
                    shmlane.lane_path_for(self.peer_id, link.shard))
                caps.append("shm1")
                hello["shm"] = {"path": str(link.lane.path), "v": 1}
            except OSError as e:
                link.lane = None
                trace.instant("bus.shm_create_failed", err=str(e))
        if self._agg_ms > 0:
            caps.append("agg1")
        if caps:
            hello["caps"] = caps
        self._send_raw(link, hello)
        for t in sorted(link.topics):
            self._send_raw(link, {"op": "sub", "topic": t})

    def _teardown_lane(self, link: _Link) -> None:
        """Detach and unlink a link's shm lane (its lifetime is the TCP
        session: a fresh ring pair is offered on every (re)connect)."""
        if link.lane is not None:
            try:
                link.lane.detach()
                link.lane.close(unlink=True)
            except OSError:
                pass
            link.lane = None
        link.shm_live = False

    def _drop(self, link: _Link) -> None:
        """Connection died: close and arm the backoff timer (reconnect
        mode / non-home shard), or propagate (legacy fail-fast mode —
        HOME shard only: one dead shard degrades, it doesn't destroy)."""
        if link.sock is not None:
            try:
                link.sock.close()
            except OSError:
                pass
            link.sock = None
        self._teardown_lane(link)
        link.fast_hub = False  # renegotiate with whatever hub comes back
        if link.shard == shardmap.HOME_SHARD and not self._reconnect:
            raise ConnectionError("bus closed")
        link.backoff = min(link.backoff * 2, 4.0) if link.backoff else 0.25
        link.next_attempt = time.monotonic() + link.backoff

    def _try_reconnect(self, link: _Link) -> bool:
        """One backoff-paced reconnect attempt; True if connected now."""
        if link.sock is not None:
            return True
        if self._closed:
            return False
        if link.shard == shardmap.HOME_SHARD and not self._reconnect:
            return False  # closed or fail-fast client: stay down
        if time.monotonic() < link.next_attempt:
            return False
        try:
            self._connect(link,
                          dial_timeout=min(max(link.backoff, 0.25), 1.0))
        except OSError:
            link.sock = None
            link.backoff = min(link.backoff * 2, 4.0) if link.backoff \
                else 0.25
            link.next_attempt = time.monotonic() + link.backoff
            return False
        trace.count("bus.reconnects")
        trace.instant("bus.reconnect", peer_id=self.peer_id,
                      shard=link.shard)
        self._flush_outbox(link)
        if self._on_reconnect and link.shard == shardmap.HOME_SHARD:
            self._on_reconnect()
        return True

    def _ensure_link(self, shard: int) -> _Link:
        """The link for ``shard``, connected lazily on first use (a shard
        nobody publishes or subscribes to is never dialed)."""
        link = self._links[shard]
        if link.sock is None and not link.attempted and not self._closed:
            # never attempted: dial now (failures arm the backoff; links
            # that HAVE died stay down until the reconnect machinery —
            # which honors the reconnect/home semantics — revives them)
            try:
                self._connect(link, dial_timeout=0.25)
            except OSError:
                link.sock = None
                link.backoff = 0.25
                link.next_attempt = time.monotonic() + link.backoff
        return link

    def _flush_outbox(self, link: _Link) -> None:
        """Replay outboxed control-plane frames owned by a link that just
        came back; frames for still-down shards stay queued.  Iterates a
        SNAPSHOT: a send failure mid-replay re-queues through
        _outbox_maybe, which must not mutate the deque being walked —
        and once the link drops again, the rest stays queued for the
        next reconnect."""
        if not self._outbox:
            return
        pending = list(self._outbox)
        self._outbox.clear()
        for i, (topic, data) in enumerate(pending):
            if shardmap.shard_of(topic, self._n) != link.shard:
                self._outbox.append((topic, data))
                continue
            if link.sock is None:
                # died mid-replay: keep this and everything after it
                for item in pending[i:]:
                    self._outbox.append(item)
                return
            self._publish_on(link, topic, data)
            self.registry.count("bus.pub_replayed", topic=topic)

    # -- protocol ---------------------------------------------------------
    def _send_raw(self, link: _Link, obj: dict) -> None:
        assert link.sock is not None
        link.sock.sendall((json.dumps(obj) + "\n").encode())

    def _send(self, link: _Link, obj: dict) -> None:
        if link.sock is None:
            self._try_reconnect(link)
        if link.sock is None:
            return  # disconnected: lossy medium, drop
        try:
            self._send_raw(link, obj)
        except OSError:
            self._drop(link)

    def _wire(self, topic: str, raw: bool) -> str:
        """The on-the-wire topic: namespaced unless ``raw`` (cross-tenant
        infrastructure addressing wire topics directly)."""
        return topic if raw else busns.wire_topic(self._ns, topic)

    def subscribe(self, topic: str, raw: bool = False) -> None:
        topic = self._wire(topic, raw)
        for s in shardmap.shards_for_subscription(topic, self._n):
            link = self._ensure_link(s)
            link.topics.add(topic)
            self._send(link, {"op": "sub", "topic": topic})

    def unsubscribe(self, topic: str, raw: bool = False) -> None:
        topic = self._wire(topic, raw)
        for s in shardmap.shards_for_subscription(topic, self._n):
            link = self._links[s]
            link.topics.discard(topic)
            self._send(link, {"op": "unsub", "topic": topic})

    def _publish_on(self, link: _Link, topic: str, data: dict) -> None:
        if link.fast_hub and " " not in topic:
            # fast framing: the hub relays on a topic peek, no JSON parse
            line = f"P{topic} " + json.dumps(data)
            # shm fast path (ISSUE 18): droppable-class frames ride the
            # c2s ring as the exact relay line (no newline); a full ring
            # falls back to TCP per frame — never a stall.  Control-plane
            # topics stay on TCP (ordered, outbox-replayed).
            if (link.shm_live and link.lane is not None
                    and not _is_control_topic(topic)):
                if link.lane.send(line.encode()):
                    self.registry.count("bus.shm_tx_frames")
                    self.registry.count("bus.msgs_sent", topic=topic)
                    self.registry.count("bus.bytes_sent", len(line) + 1,
                                        topic=topic)
                    return
                self.registry.count("bus.shm_fallbacks")
        else:
            line = json.dumps({"op": "pub", "topic": topic, "data": data})
        try:
            wire = (line + "\n").encode()
            link.sock.sendall(wire)
            # count ACTUAL wire bytes (framed line + newline), per topic
            self.registry.count("bus.msgs_sent", topic=topic)
            self.registry.count("bus.bytes_sent", len(wire), topic=topic)
        except OSError:
            self.registry.count("bus.send_drops")
            self._outbox_maybe(topic, data)
            self._drop(link)

    def _outbox_maybe(self, topic: str, data: dict) -> None:
        """Queue a dropped frame for replay-on-reconnect — control-plane
        topics only (droppable beacon streams are superseded by the next
        beat; replaying them would only add stale load)."""
        if self._outbox_max <= 0 or not _is_control_topic(topic):
            return
        if len(self._outbox) == self._outbox.maxlen:
            self.registry.count("bus.outbox_overflow")
        self._outbox.append((topic, data))

    def publish(self, topic: str, data: dict, raw: bool = False) -> None:
        topic = self._wire(topic, raw)
        link = self._ensure_link(shardmap.shard_of(topic, self._n))
        if link.sock is None:
            self._try_reconnect(link)
        if link.sock is None:
            # dropped frames are NOT counted as sent (matches C++); they
            # ARE counted as drops, and control-plane frames queue for
            # replay when the owning shard comes back
            self.registry.count("bus.pub_dropped_disconnected", topic=topic)
            self._outbox_maybe(topic, data)
            return
        self._publish_on(link, topic, data)

    def query_peers(self, topic: str, raw: bool = False) -> None:
        self._send(self._links[shardmap.HOME_SHARD],
                   {"op": "peers", "topic": self._wire(topic, raw)})

    # -- receive ----------------------------------------------------------
    def _deliver_topic(self, topic: str) -> str:
        """Strip THIS client's namespace off a delivered wire topic, so
        consumers see the logical topic they subscribed (an un-namespaced
        client — e.g. solverd serving many tenants — sees wire topics
        verbatim)."""
        if self._ns_prefix and topic.startswith(self._ns_prefix):
            return topic[len(self._ns_prefix):]
        return topic

    def _explode_agg1(self, topic: str, data: dict) -> Optional[dict]:
        """A coalesced ``agg1`` region frame -> the first per-peer pos1
        msg dict (the rest queue on ``self._pending``), so consumers see
        the same singles stream the hub would have sent without
        aggregation.  Malformed aggregates are dropped and counted —
        never surfaced (a bad frame must not crash a role loop)."""
        try:
            entries, _ = plan_codec.decode_agg1_b64(data.get("data") or "")
        except plan_codec.CodecError:
            self.registry.count("bus.agg_rx_malformed")
            return None
        if not entries:
            return None
        self.registry.count("bus.agg_rx_frames")
        self.registry.count("bus.agg_rx_entries", len(entries))
        msgs = [{"op": "msg", "topic": topic, "from": name,
                 "data": {"type": "pos1",
                          "data": base64.b64encode(blob).decode()}}
                for name, blob in entries]
        self._pending.extend(msgs[1:])
        return msgs[0]

    def _parse_line(self, link: _Link, line: bytes) -> Optional[dict]:
        """One framed line -> normalized frame dict, or None to skip."""
        if line[:1] == b"M":
            # fast relay frame: `M<topic> <from> <payload-json>` —
            # normalized to the legacy msg-dict shape for callers
            head, _, payload = line.partition(b" ")
            sender, _, payload = payload.partition(b" ")
            try:
                data = json.loads(payload)
            except json.JSONDecodeError:
                return None  # garbage payload: ignore like any frame
            topic = head[1:].decode(errors="replace")
            self.registry.count("bus.msgs_received", topic=topic)
            self.registry.count("bus.bytes_received", len(line) + 1,
                                topic=topic)
            if isinstance(data, dict) and data.get("type") == "agg1":
                return self._explode_agg1(self._deliver_topic(topic), data)
            return {"op": "msg", "topic": self._deliver_topic(topic),
                    "from": sender.decode(errors="replace"),
                    "data": data}
        try:
            frame = json.loads(line)
        except json.JSONDecodeError:
            return None
        if frame.get("op") == "msg":
            # wire bytes: the framed line plus its newline
            topic = frame.get("topic", "")
            self.registry.count("bus.msgs_received", topic=topic)
            self.registry.count("bus.bytes_received", len(line) + 1,
                                topic=topic)
            frame["topic"] = self._deliver_topic(topic)
            data = frame.get("data")
            if isinstance(data, dict) and data.get("type") == "agg1":
                return self._explode_agg1(frame["topic"], data)
        elif frame.get("op") == "welcome":
            # caps negotiation: switch publishes to fast framing only
            # when the hub advertises it (old hub -> legacy), per link
            link.hub_caps = frame.get("caps") or []
            link.fast_hub = (self._fastframe
                             and "relay1" in link.hub_caps)
            # the lane goes live only when the hub echoes shm1 (it
            # attached our rings); otherwise tear down the offer — an
            # old hub, a refused attach, or JG_BUS_SHM=0 hub-side
            if link.lane is not None:
                link.shm_live = "shm1" in link.hub_caps
                if not link.shm_live:
                    self._teardown_lane(link)
        return frame

    def _next_buffered(self) -> Optional[dict]:
        """Pop the next complete frame already buffered on any link
        (round-robin across shards, so one busy shard cannot starve the
        others).  Frames already exploded/drained ahead of the TCP
        buffers (agg1 entries, lane deliveries) come first — they are
        older than anything still framed."""
        if self._pending:
            return self._pending.popleft()
        for k in range(self._n):
            link = self._links[(self._rr + k) % self._n]
            while True:
                nl = link.buf.find(b"\n")
                if nl < 0:
                    break
                line = link.buf[:nl]
                link.buf = link.buf[nl + 1:]
                frame = self._parse_line(link, line)
                if frame is not None:
                    self._rr = (link.shard + 1) % self._n
                    return frame
        return None

    def _drain_lanes(self) -> None:
        """Pull every frame waiting in live s2c rings onto the pending
        queue.  Lane frames are the exact relay ``M`` lines (no
        newline), so they reuse :meth:`_parse_line` unchanged."""
        for link in self._links:
            lane = link.lane
            if lane is None or not link.shm_live:
                continue
            lane.unpark()  # also drains accumulated doorbell bytes
            while True:
                raw = lane.recv()
                if raw is None:
                    break
                self.registry.count("bus.shm_rx_frames")
                parsed = self._parse_line(link, raw)
                if parsed is not None:
                    self._pending.append(parsed)

    def _park_lanes(self) -> bool:
        """Arm every live lane's parked flag so the hub rings the
        doorbell; False when frames raced in (caller must drain before
        sleeping — the classic lost-wakeup guard)."""
        ok = True
        for link in self._links:
            if link.lane is not None and link.shm_live:
                if not link.lane.park():
                    ok = False
        return ok

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next frame (any op, any shard) or None on timeout.  In
        reconnect mode an outage reads as a timeout (backoff-paced
        reconnect attempts ride each call); a non-home shard outage never
        raises — its regions degrade while the rest of the pool flows."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._drain_lanes()
            frame = self._next_buffered()
            if frame is not None:
                return frame
            for link in self._links:
                if link.sock is None and link.next_attempt > 0.0:
                    self._try_reconnect(link)
            socks = [link.sock for link in self._links
                     if link.sock is not None]
            if not socks:
                # everything down: wait out the lesser of caller timeout /
                # the nearest due attempt (matches the old outage wait)
                wait = max((link.next_attempt for link in self._links),
                           default=0.0) - time.monotonic()
                wait = max(0.0, wait)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                time.sleep(min(wait, 0.25))
                if deadline is not None and time.monotonic() >= deadline:
                    return None
                continue
            slice_s = 0.25 if deadline is None else \
                max(0.001, min(0.25, deadline - time.monotonic()))
            if deadline is not None and deadline - time.monotonic() <= 0:
                return None
            # park live lanes so the hub rings the doorbell while we
            # sleep; a failed park means frames raced in — drain first
            if not self._park_lanes():
                continue
            rlist = socks + [link.lane.bell_fd() for link in self._links
                             if link.lane is not None and link.shm_live
                             and link.lane.bell_fd() >= 0]
            try:
                readable, _, _ = select.select(rlist, [], [], slice_s)
            except (OSError, ValueError):
                readable = []  # a sock died mid-select: sweep below
            if not readable and deadline is not None \
                    and time.monotonic() >= deadline:
                return None
            for sock in readable:
                if isinstance(sock, int):
                    continue  # doorbell fd: lanes drain at loop top
                link = next(l for l in self._links if l.sock is sock)
                try:
                    sock.settimeout(self._timeout)
                    chunk = sock.recv(65536)
                except socket.timeout:
                    continue
                except OSError:
                    self._drop(link)
                    continue
                if not chunk:
                    self._drop(link)
                    continue
                link.buf += chunk

    def messages(self, duration: float) -> Iterator[dict]:
        """Application messages received within ``duration`` seconds."""
        deadline = time.monotonic() + duration
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            frame = self.recv(timeout=remaining)
            if frame and frame.get("op") == "msg":
                yield frame

    def close(self) -> None:
        self._reconnect = False
        self._closed = True
        for link in self._links:
            if link.sock is not None:
                try:
                    link.sock.close()
                except OSError:
                    pass
                link.sock = None
            self._teardown_lane(link)
