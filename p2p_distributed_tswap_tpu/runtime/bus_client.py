"""Python client for the host-runtime message bus (cpp/busd).

Speaks the same line-framed JSON protocol as the C++ BusClient
(cpp/common/bus.hpp); used by the solver daemon, the process-spawn test
runner, and integration tests.

Relay fast framing (ISSUE 4, caps-negotiated): the client advertises
``caps:["relay1"]`` in hello; once the hub's welcome echoes the cap,
publishes switch to topic-prefix lines the hub relays without JSON
parsing (``P<topic> <payload>``), and deliveries may arrive as
``M<topic> <from> <payload>`` — :meth:`recv` normalizes those to the
same ``{"op":"msg","topic","from","data"}`` dict, so consumers are
agnostic.  ``JG_BUS_FASTFRAME=0`` (or ``fastframe=False``) pins the
client to the legacy JSON wire; against an old hub (welcome without
caps) it stays legacy automatically.  A topic ending in ``.*``
subscribes by prefix (busd wildcard matching — managers use
``mapd.pos.*`` for the region-sharded position gossip).

Like the C++ client, it can survive a bus restart: with ``reconnect=True``
a dropped connection is retried with exponential backoff (0.25 s .. 4 s);
on success the client re-sends hello, re-subscribes every topic, and calls
``on_reconnect``.  While disconnected, ``publish`` drops (the bus is a
lossy broadcast medium) and ``recv`` behaves like a timeout.  The
reference's brokerless gossipsub mesh has no hub to lose — with this,
losing busd degrades the fleet instead of destroying it (VERDICT r2
item 5).

Network accounting lives in the unified live-metrics registry
(obs/registry.py): per-topic ``bus.msgs_sent`` / ``bus.bytes_sent`` /
``bus.msgs_received`` / ``bus.bytes_received`` counters, counting ACTUAL
wire bytes (the framed line including its newline — the pre-registry
NetworkMetrics counted the unframed line, so py and cpp bandwidth numbers
disagreed by one byte per message).  ``registry.network_summary()`` is the
rolled-up view; the ``mapd.metrics`` beacon ships the raw counters.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Callable, Iterator, Optional

from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.obs import trace


class BusClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7400,
                 peer_id: Optional[str] = None, timeout: float = 5.0,
                 reconnect: bool = False,
                 on_reconnect: Optional[Callable[[], None]] = None,
                 registry: Optional[_reg.Registry] = None,
                 fastframe: Optional[bool] = None):
        self.peer_id = peer_id or f"py-{int(time.time() * 1000) % 10 ** 10}"
        self._host, self._port, self._timeout = host, port, timeout
        self._reconnect = reconnect
        self._on_reconnect = on_reconnect
        self._topics: set[str] = set()
        self._backoff = 0.0
        self._next_attempt = 0.0
        # relay fast framing: advertised in hello, armed by the hub's
        # welcome (see module docstring); None = the JG_BUS_FASTFRAME env
        self._fastframe = (os.environ.get("JG_BUS_FASTFRAME", "1")
                           not in ("0", "false", "")
                           if fastframe is None else fastframe)
        self.hub_caps: Optional[list] = None  # from the last welcome
        self._fast_hub = False
        self.sock: Optional[socket.socket] = None
        # network accounting sink: the process registry unless a test
        # injects its own (obs/registry.py is the single source of truth)
        self.registry = registry or _reg.get_registry()
        self._connect()  # initial connect still raises: startup contract

    # -- connection management -------------------------------------------
    def _connect(self) -> None:
        self.sock = socket.create_connection((self._host, self._port),
                                             timeout=self._timeout)
        self.sock.settimeout(self._timeout)
        self._buf = b""
        self._backoff = 0.0
        self._fast_hub = False  # renegotiated by the hub's welcome
        hello = {"op": "hello", "peer_id": self.peer_id}
        if self._fastframe:
            hello["caps"] = ["relay1"]
        self._send_raw(hello)
        for t in sorted(self._topics):
            self._send_raw({"op": "sub", "topic": t})

    def _drop(self) -> None:
        """Connection died: close and arm the backoff timer (reconnect
        mode), or propagate (legacy fail-fast mode)."""
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        self._fast_hub = False  # renegotiate with whatever hub comes back
        if not self._reconnect:
            raise ConnectionError("bus closed")
        self._backoff = min(self._backoff * 2, 4.0) if self._backoff else 0.25
        self._next_attempt = time.monotonic() + self._backoff

    def _try_reconnect(self) -> bool:
        """One backoff-paced reconnect attempt; True if connected now."""
        if self.sock is not None:
            return True
        if not self._reconnect:
            return False  # closed or fail-fast client: stay down
        if time.monotonic() < self._next_attempt:
            return False
        try:
            self._connect()
        except OSError:
            self.sock = None
            self._backoff = min(self._backoff * 2, 4.0) if self._backoff \
                else 0.25
            self._next_attempt = time.monotonic() + self._backoff
            return False
        trace.count("bus.reconnects")
        trace.instant("bus.reconnect", peer_id=self.peer_id)
        if self._on_reconnect:
            self._on_reconnect()
        return True

    @property
    def connected(self) -> bool:
        return self.sock is not None

    @property
    def fast_hub(self) -> bool:
        """True once the hub's welcome negotiated the relay1 framing."""
        return self._fast_hub

    # -- protocol ---------------------------------------------------------
    def _send_raw(self, obj: dict) -> None:
        assert self.sock is not None
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def _send(self, obj: dict) -> None:
        if self.sock is None:
            self._try_reconnect()
        if self.sock is None:
            return  # disconnected: lossy medium, drop
        try:
            self._send_raw(obj)
        except OSError:
            self._drop()

    def subscribe(self, topic: str) -> None:
        self._topics.add(topic)
        self._send({"op": "sub", "topic": topic})

    def unsubscribe(self, topic: str) -> None:
        self._topics.discard(topic)
        self._send({"op": "unsub", "topic": topic})

    def publish(self, topic: str, data: dict) -> None:
        if self._fast_hub and " " not in topic:
            # fast framing: the hub relays on a topic peek, no JSON parse
            line = f"P{topic} " + json.dumps(data)
        else:
            line = json.dumps({"op": "pub", "topic": topic, "data": data})
        if self.sock is None:
            self._try_reconnect()
        if self.sock is None:
            return  # dropped frames are NOT counted as sent (matches C++)
        try:
            wire = (line + "\n").encode()
            self.sock.sendall(wire)
            # count ACTUAL wire bytes (framed line + newline), per topic
            self.registry.count("bus.msgs_sent", topic=topic)
            self.registry.count("bus.bytes_sent", len(wire), topic=topic)
        except OSError:
            self.registry.count("bus.send_drops")
            self._drop()

    def query_peers(self, topic: str) -> None:
        self._send({"op": "peers", "topic": topic})

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next frame (any op) or None on timeout.  In reconnect mode an
        outage reads as a timeout (reconnect attempts ride each call)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.sock is None:
                if not self._try_reconnect():
                    # wait out the lesser of caller timeout / next attempt
                    wait = max(0.0, self._next_attempt - time.monotonic())
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return None
                        wait = min(wait, remaining)
                    time.sleep(min(wait, 0.25))
                    if deadline is not None and time.monotonic() >= deadline:
                        return None
                    continue
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = self._buf[:nl]
                self._buf = self._buf[nl + 1:]
                if line[:1] == b"M":
                    # fast relay frame: `M<topic> <from> <payload-json>` —
                    # normalized to the legacy msg-dict shape for callers
                    head, _, payload = line.partition(b" ")
                    sender, _, payload = payload.partition(b" ")
                    try:
                        data = json.loads(payload)
                    except json.JSONDecodeError:
                        continue  # garbage payload: ignore like any frame
                    topic = head[1:].decode(errors="replace")
                    self.registry.count("bus.msgs_received", topic=topic)
                    self.registry.count("bus.bytes_received", len(line) + 1,
                                        topic=topic)
                    return {"op": "msg", "topic": topic,
                            "from": sender.decode(errors="replace"),
                            "data": data}
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if frame.get("op") == "msg":
                    # wire bytes: the framed line plus its newline
                    topic = frame.get("topic", "")
                    self.registry.count("bus.msgs_received", topic=topic)
                    self.registry.count("bus.bytes_received", len(line) + 1,
                                        topic=topic)
                elif frame.get("op") == "welcome":
                    # caps negotiation: switch publishes to fast framing
                    # only when the hub advertises it (old hub -> legacy)
                    self.hub_caps = frame.get("caps") or []
                    self._fast_hub = (self._fastframe
                                      and "relay1" in self.hub_caps)
                return frame
            try:
                self.sock.settimeout(
                    None if deadline is None
                    else max(0.001, deadline - time.monotonic()))
                chunk = self.sock.recv(65536)
            except socket.timeout:
                return None
            except OSError:
                self._drop()
                continue
            if not chunk:
                self._drop()
                continue
            self._buf += chunk

    def messages(self, duration: float) -> Iterator[dict]:
        """Application messages received within ``duration`` seconds."""
        deadline = time.monotonic() + duration
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            frame = self.recv(timeout=remaining)
            if frame and frame.get("op") == "msg":
                yield frame

    def close(self) -> None:
        self._reconnect = False
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
