"""Python client for the host-runtime message bus (cpp/busd).

Speaks the same line-framed JSON protocol as the C++ BusClient
(cpp/common/bus.hpp); used by the solver daemon, the process-spawn test
runner, and integration tests.

Like the C++ client, it can survive a bus restart: with ``reconnect=True``
a dropped connection is retried with exponential backoff (0.25 s .. 4 s);
on success the client re-sends hello, re-subscribes every topic, and calls
``on_reconnect``.  While disconnected, ``publish`` drops (the bus is a
lossy broadcast medium) and ``recv`` behaves like a timeout.  The
reference's brokerless gossipsub mesh has no hub to lose — with this,
losing busd degrades the fleet instead of destroying it (VERDICT r2
item 5).

Network accounting lives in the unified live-metrics registry
(obs/registry.py): per-topic ``bus.msgs_sent`` / ``bus.bytes_sent`` /
``bus.msgs_received`` / ``bus.bytes_received`` counters, counting ACTUAL
wire bytes (the framed line including its newline — the pre-registry
NetworkMetrics counted the unframed line, so py and cpp bandwidth numbers
disagreed by one byte per message).  ``registry.network_summary()`` is the
rolled-up view; the ``mapd.metrics`` beacon ships the raw counters.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Callable, Iterator, Optional

from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.obs import trace


class BusClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7400,
                 peer_id: Optional[str] = None, timeout: float = 5.0,
                 reconnect: bool = False,
                 on_reconnect: Optional[Callable[[], None]] = None,
                 registry: Optional[_reg.Registry] = None):
        self.peer_id = peer_id or f"py-{int(time.time() * 1000) % 10 ** 10}"
        self._host, self._port, self._timeout = host, port, timeout
        self._reconnect = reconnect
        self._on_reconnect = on_reconnect
        self._topics: set[str] = set()
        self._backoff = 0.0
        self._next_attempt = 0.0
        self.sock: Optional[socket.socket] = None
        # network accounting sink: the process registry unless a test
        # injects its own (obs/registry.py is the single source of truth)
        self.registry = registry or _reg.get_registry()
        self._connect()  # initial connect still raises: startup contract

    # -- connection management -------------------------------------------
    def _connect(self) -> None:
        self.sock = socket.create_connection((self._host, self._port),
                                             timeout=self._timeout)
        self.sock.settimeout(self._timeout)
        self._buf = b""
        self._backoff = 0.0
        self._send_raw({"op": "hello", "peer_id": self.peer_id})
        for t in sorted(self._topics):
            self._send_raw({"op": "sub", "topic": t})

    def _drop(self) -> None:
        """Connection died: close and arm the backoff timer (reconnect
        mode), or propagate (legacy fail-fast mode)."""
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        if not self._reconnect:
            raise ConnectionError("bus closed")
        self._backoff = min(self._backoff * 2, 4.0) if self._backoff else 0.25
        self._next_attempt = time.monotonic() + self._backoff

    def _try_reconnect(self) -> bool:
        """One backoff-paced reconnect attempt; True if connected now."""
        if self.sock is not None:
            return True
        if not self._reconnect:
            return False  # closed or fail-fast client: stay down
        if time.monotonic() < self._next_attempt:
            return False
        try:
            self._connect()
        except OSError:
            self.sock = None
            self._backoff = min(self._backoff * 2, 4.0) if self._backoff \
                else 0.25
            self._next_attempt = time.monotonic() + self._backoff
            return False
        trace.count("bus.reconnects")
        trace.instant("bus.reconnect", peer_id=self.peer_id)
        if self._on_reconnect:
            self._on_reconnect()
        return True

    @property
    def connected(self) -> bool:
        return self.sock is not None

    # -- protocol ---------------------------------------------------------
    def _send_raw(self, obj: dict) -> None:
        assert self.sock is not None
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def _send(self, obj: dict) -> None:
        if self.sock is None:
            self._try_reconnect()
        if self.sock is None:
            return  # disconnected: lossy medium, drop
        try:
            self._send_raw(obj)
        except OSError:
            self._drop()

    def subscribe(self, topic: str) -> None:
        self._topics.add(topic)
        self._send({"op": "sub", "topic": topic})

    def publish(self, topic: str, data: dict) -> None:
        line = json.dumps({"op": "pub", "topic": topic, "data": data})
        if self.sock is None:
            self._try_reconnect()
        if self.sock is None:
            return  # dropped frames are NOT counted as sent (matches C++)
        try:
            wire = (line + "\n").encode()
            self.sock.sendall(wire)
            # count ACTUAL wire bytes (framed line + newline), per topic
            self.registry.count("bus.msgs_sent", topic=topic)
            self.registry.count("bus.bytes_sent", len(wire), topic=topic)
        except OSError:
            self.registry.count("bus.send_drops")
            self._drop()

    def query_peers(self, topic: str) -> None:
        self._send({"op": "peers", "topic": topic})

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next frame (any op) or None on timeout.  In reconnect mode an
        outage reads as a timeout (reconnect attempts ride each call)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.sock is None:
                if not self._try_reconnect():
                    # wait out the lesser of caller timeout / next attempt
                    wait = max(0.0, self._next_attempt - time.monotonic())
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return None
                        wait = min(wait, remaining)
                    time.sleep(min(wait, 0.25))
                    if deadline is not None and time.monotonic() >= deadline:
                        return None
                    continue
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = self._buf[:nl]
                self._buf = self._buf[nl + 1:]
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if frame.get("op") == "msg":
                    # wire bytes: the framed line plus its newline
                    topic = frame.get("topic", "")
                    self.registry.count("bus.msgs_received", topic=topic)
                    self.registry.count("bus.bytes_received", len(line) + 1,
                                        topic=topic)
                return frame
            try:
                self.sock.settimeout(
                    None if deadline is None
                    else max(0.001, deadline - time.monotonic()))
                chunk = self.sock.recv(65536)
            except socket.timeout:
                return None
            except OSError:
                self._drop()
                continue
            if not chunk:
                self._drop()
                continue
            self._buf += chunk

    def messages(self, duration: float) -> Iterator[dict]:
        """Application messages received within ``duration`` seconds."""
        deadline = time.monotonic() + duration
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            frame = self.recv(timeout=remaining)
            if frame and frame.get("op") == "msg":
                yield frame

    def close(self) -> None:
        self._reconnect = False
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
