"""Python client for the host-runtime message bus (cpp/busd).

Speaks the same line-framed JSON protocol as the C++ BusClient
(cpp/common/bus.hpp); used by the solver daemon, the process-spawn test
runner, and integration tests.
"""

from __future__ import annotations

import json
import socket
import time
from typing import Callable, Iterator, Optional

from p2p_distributed_tswap_tpu.metrics.task_metrics import NetworkMetrics


class BusClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 7400,
                 peer_id: Optional[str] = None, timeout: float = 5.0):
        self.peer_id = peer_id or f"py-{int(time.time() * 1000) % 10 ** 10}"
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._buf = b""
        self.net = NetworkMetrics()
        self._send({"op": "hello", "peer_id": self.peer_id})

    def _send(self, obj: dict) -> None:
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def subscribe(self, topic: str) -> None:
        self._send({"op": "sub", "topic": topic})

    def publish(self, topic: str, data: dict) -> None:
        frame = {"op": "pub", "topic": topic, "data": data}
        line = json.dumps(frame)
        self.net.record_sent(len(line))
        self.sock.sendall((line + "\n").encode())

    def query_peers(self, topic: str) -> None:
        self._send({"op": "peers", "topic": topic})

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next frame (any op) or None on timeout."""
        self.sock.settimeout(timeout)
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = self._buf[:nl]
                self._buf = self._buf[nl + 1:]
                try:
                    frame = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if frame.get("op") == "msg":
                    self.net.record_received(len(line))
                return frame
            try:
                chunk = self.sock.recv(65536)
            except socket.timeout:
                return None
            if not chunk:
                raise ConnectionError("bus closed")
            self._buf += chunk

    def messages(self, duration: float) -> Iterator[dict]:
        """Application messages received within ``duration`` seconds."""
        deadline = time.monotonic() + duration
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            frame = self.recv(timeout=remaining)
            if frame and frame.get("op") == "msg":
                yield frame

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
