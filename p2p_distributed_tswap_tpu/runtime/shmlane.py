"""Zero-copy same-host bus lanes — the "shm1" shared-memory ring transport.

The message plane's measured wall is the kernel socket path: busd relays
at ~3.5 µs/msg even on the fast frames (results/bus_scaling_r08.json) and
the profiling plane attributes 58% of a small fleet's wall clock to
``bus_client:recv`` (results/prof_r18.flame.folded) — a write(2), a
wakeup, and a read(2) per frame per peer.  Same-host peers don't need any
of that: this module maps one small file per (client, busd-shard) pair
into both address spaces and moves the EXACT fast-path frames (the
``P<topic> <payload>`` / ``M<topic> <from> <payload>`` lines of the
relay1 framing, ISSUE 4) through a pair of single-producer
single-consumer rings, so the steady-state cost per frame is a memcpy
plus two relaxed cursor stores.

Layout of a lane file (version "SHL1", all little-endian; the C++ mirror
``cpp/common/shmlane.hpp`` is layout-identical and both sides validate
magic/version/geometry before attaching):

    0    u32 magic        "SHL1" (0x314C4853)
    4    u16 version      1
    6    u16 reserved
    8    u32 slot_size    payload capacity per slot (bytes)
    12   u32 nslots       slots per ring (power of two)
    16   u32 creator_pid  the client that built the file (stale-lane
                          reclaim checks its liveness on reconnect)
    20   u32 attached_pid busd's pid once it mapped the lane (0 = never)
    24   u32 detached     either side stores 1: lane is torn down and
                          every frame goes back to TCP (never a stall)
    64   c2s ring head    u64 (client writes; monotone slot sequence)
    128  c2s ring tail    u64 (busd writes)
    192  c2s parked       u32 (busd is blocked in poll; writer rings the
                          doorbell after clearing it)
    256  s2c ring head    u64 (busd writes)
    320  s2c ring tail    u64 (client writes)
    384  s2c parked       u32
    4096 c2s slots        nslots * stride   stride = 64-byte-rounded
    ...  s2c slots        nslots * stride   (4 + slot_size)

Each slot is ``u32 len`` + payload.  SPSC discipline: the writer fills
the slot at ``head % nslots``, then publishes ``head+1``; the reader
consumes at ``tail % nslots`` and publishes ``tail+1``.  Cursors are
8-byte aligned and each side writes only its own, so plain mapped stores
are safe on every platform the runtime targets (x86-64/aarch64 TSO-ish
ordering; the C++ side uses real atomics).

Doorbell: a reader that finds the ring empty PARKS — it stores 1 to its
``parked`` word, re-checks the ring (the standard lost-wakeup guard), and
blocks in poll/select on a named FIFO next to the lane file.  A writer
that observes ``parked == 1`` clears it and writes one byte to the FIFO.
Under load the reader never parks and the doorbell never fires — the
spin-then-park shape that turns the 58% recv-park into a ring poll.
(An eventfd would be the single-process choice; the doorbell must cross
unrelated processes that only share a filesystem, which is exactly what
a FIFO is.)

Overflow / death contract (ISSUE 18): a full ring NEVER blocks the
writer — the frame falls back to the TCP link verbatim and
``bus.shm_fallbacks`` counts it.  Only the droppable stream class rides
the lane (position beacons, metrics, path samples — busd's own shed
class), so a rare TCP/ring interleave reorders nothing the consumers
don't already tolerate; the ordered control plane stays on TCP, which
also carries oversized frames and remains the only transport for
cross-host links.  A dead peer (pid gone, or the TCP session it rode on
closed) tears the lane down; a stale lane file left by a dead client is
reclaimed (unlinked and rebuilt) on the next connect.

Kill switch: lanes are offered only when ``JG_BUS_SHM`` is truthy; unset
(the default) keeps the TCP wire byte-identical — pinned by
tests/test_shmlane.py against a raw socket.
"""

from __future__ import annotations

import errno
import mmap
import os
import stat
import struct
import tempfile
from pathlib import Path
from typing import List, Optional, Tuple

MAGIC = 0x314C4853  # b"SHL1" little-endian
VERSION = 1
DEFAULT_SLOT_SIZE = 768
DEFAULT_NSLOTS = 256
HEADER_BYTES = 4096

_OFF_MAGIC = 0
_OFF_VERSION = 4
_OFF_SLOT_SIZE = 8
_OFF_NSLOTS = 12
_OFF_CREATOR_PID = 16
_OFF_ATTACHED_PID = 20
_OFF_DETACHED = 24
# per-ring control offsets (cacheline-separated)
_RING_CTRL = ((64, 128, 192), (256, 320, 384))  # (head, tail, parked)

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

SHM_ENV = "JG_BUS_SHM"
SHM_DIR_ENV = "JG_BUS_SHM_DIR"


def shm_enabled() -> bool:
    """Lanes are OPT-IN: offered only when JG_BUS_SHM is a truthy value.
    Unset/0 keeps the TCP wire byte-identical (the pin test's contract)."""
    return os.environ.get(SHM_ENV, "") not in ("", "0", "false")


def lane_dir() -> Path:
    """Where lane files live: JG_BUS_SHM_DIR (the fleet runner points it
    at the run dir) or a per-uid tmp subdir."""
    d = os.environ.get(SHM_DIR_ENV, "")
    if d:
        p = Path(d)
    else:
        p = Path(tempfile.gettempdir()) / f"jg_shm_{os.getuid()}"
    p.mkdir(parents=True, exist_ok=True)
    return p


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _round_up(n: int, align: int) -> int:
    return (n + align - 1) // align * align


class _Ring:
    """One SPSC ring over a shared mapping.  The same class serves both
    roles; the owner of each cursor is fixed by the lane direction."""

    def __init__(self, mm: mmap.mmap, ctrl: Tuple[int, int, int],
                 data_off: int, nslots: int, slot_size: int):
        self._mm = mm
        self._head_off, self._tail_off, self._parked_off = ctrl
        self._data_off = data_off
        self._nslots = nslots
        self._slot_size = slot_size
        self._stride = _round_up(4 + slot_size, 64)

    # cursor accessors (8-byte aligned single-word loads/stores)
    def _load(self, off: int) -> int:
        return _U64.unpack_from(self._mm, off)[0]

    def _store(self, off: int, v: int) -> None:
        _U64.pack_into(self._mm, off, v)

    @property
    def head(self) -> int:
        return self._load(self._head_off)

    @property
    def tail(self) -> int:
        return self._load(self._tail_off)

    def empty(self) -> bool:
        return self.tail >= self.head

    def push(self, payload: bytes) -> bool:
        """Write one frame; False when it doesn't fit (ring full or
        oversized payload) — the caller falls back to TCP, never blocks."""
        if len(payload) > self._slot_size:
            return False
        head = self.head
        if head - self.tail >= self._nslots:
            return False
        off = self._data_off + (head % self._nslots) * self._stride
        self._mm[off + 4:off + 4 + len(payload)] = payload
        _U32.pack_into(self._mm, off, len(payload))
        # publish AFTER the slot contents: the reader acquires via head
        self._store(self._head_off, head + 1)
        return True

    def pop(self) -> Optional[bytes]:
        tail = self.tail
        if tail >= self.head:
            return None
        off = self._data_off + (tail % self._nslots) * self._stride
        (n,) = _U32.unpack_from(self._mm, off)
        out = bytes(self._mm[off + 4:off + 4 + n])
        self._store(self._tail_off, tail + 1)
        return out

    # -- spin-then-park doorbell protocol ---------------------------------
    def reader_park(self) -> bool:
        """Announce the reader is about to block.  Returns False when the
        ring became non-empty in the race window (caller must drain
        instead of blocking)."""
        _U32.pack_into(self._mm, self._parked_off, 1)
        if not self.empty():
            _U32.pack_into(self._mm, self._parked_off, 0)
            return False
        return True

    def reader_unpark(self) -> None:
        _U32.pack_into(self._mm, self._parked_off, 0)

    def reader_parked(self) -> bool:
        return _U32.unpack_from(self._mm, self._parked_off)[0] != 0

    def writer_should_ring(self) -> bool:
        """After a push: True once per park — clears the flag so one
        doorbell byte wakes the reader however many frames follow."""
        if _U32.unpack_from(self._mm, self._parked_off)[0]:
            _U32.pack_into(self._mm, self._parked_off, 0)
            return True
        return False


class ShmLane:
    """One mapped lane: a c2s and an s2c ring plus their doorbells.

    ``role`` is "client" (creates the file, writes c2s, reads s2c) or
    "hub" (attaches, reads c2s, writes s2c).
    """

    def __init__(self, path: Path, role: str, mm: mmap.mmap,
                 slot_size: int, nslots: int):
        assert role in ("client", "hub")
        self.path = Path(path)
        self.role = role
        self._mm = mm
        self.slot_size = slot_size
        self.nslots = nslots
        stride = _round_up(4 + slot_size, 64)
        c2s = _Ring(mm, _RING_CTRL[0], HEADER_BYTES, nslots, slot_size)
        s2c = _Ring(mm, _RING_CTRL[1], HEADER_BYTES + nslots * stride,
                    nslots, slot_size)
        self.tx = c2s if role == "client" else s2c
        self.rx = s2c if role == "client" else c2s
        self._bell_rx_fd = -1  # our read side (parked reader wakes here)
        self._bell_tx_fd = -1  # peer's bell (opened lazily on first ring)
        self._open_bell_rx()

    # -- lane file naming -------------------------------------------------
    @staticmethod
    def bell_paths(path: Path) -> Tuple[Path, Path]:
        """(c2s bell, s2c bell) FIFOs next to the lane file."""
        return (Path(str(path) + ".c2s.bell"),
                Path(str(path) + ".s2c.bell"))

    def _bell_rx_path(self) -> Path:
        c2s, s2c = self.bell_paths(self.path)
        return s2c if self.role == "client" else c2s

    def _bell_tx_path(self) -> Path:
        c2s, s2c = self.bell_paths(self.path)
        return c2s if self.role == "client" else s2c

    def _open_bell_rx(self) -> None:
        try:
            self._bell_rx_fd = os.open(self._bell_rx_path(),
                                       os.O_RDONLY | os.O_NONBLOCK)
        except OSError:
            self._bell_rx_fd = -1  # no doorbell: poll-timeout paced

    # -- header fields ----------------------------------------------------
    def _get_u32(self, off: int) -> int:
        return _U32.unpack_from(self._mm, off)[0]

    def _set_u32(self, off: int, v: int) -> None:
        _U32.pack_into(self._mm, off, v)

    @property
    def creator_pid(self) -> int:
        return self._get_u32(_OFF_CREATOR_PID)

    @property
    def attached_pid(self) -> int:
        return self._get_u32(_OFF_ATTACHED_PID)

    @property
    def detached(self) -> bool:
        return self._get_u32(_OFF_DETACHED) != 0

    def mark_attached(self, pid: int) -> None:
        self._set_u32(_OFF_ATTACHED_PID, pid)

    def detach(self) -> None:
        """Tear the lane down: both sides observe ``detached`` and route
        every subsequent frame over TCP."""
        self._set_u32(_OFF_DETACHED, 1)

    def peer_alive(self) -> bool:
        """The OTHER side's pid still exists (hub checks the creator,
        client checks whoever attached; an unattached lane reads alive —
        negotiation may still be in flight)."""
        pid = (self.attached_pid if self.role == "client"
               else self.creator_pid)
        return pid == 0 or _pid_alive(pid)

    # -- frame I/O --------------------------------------------------------
    def send(self, frame: bytes) -> bool:
        """Push one frame (the exact relay line, no trailing newline);
        rings the peer's doorbell if it parked.  False = caller must use
        TCP (full / oversized / torn down)."""
        if self.detached:
            return False
        if not self.tx.push(frame):
            return False
        if self.tx.writer_should_ring():
            self._ring_bell()
        return True

    def recv(self) -> Optional[bytes]:
        return self.rx.pop()

    def rx_pending(self) -> bool:
        return not self.rx.empty()

    def _ring_bell(self) -> None:
        if self._bell_tx_fd < 0:
            try:
                self._bell_tx_fd = os.open(self._bell_tx_path(),
                                           os.O_WRONLY | os.O_NONBLOCK)
            except OSError:
                return  # peer's read side not open yet: it isn't parked
        try:
            os.write(self._bell_tx_fd, b"x")
        except OSError as e:
            if e.errno in (errno.EPIPE, errno.ENXIO):
                try:
                    os.close(self._bell_tx_fd)
                except OSError:
                    pass
                self._bell_tx_fd = -1
            # EAGAIN: bell already full of wakeup bytes — that's a wakeup

    # -- parking (reader side) -------------------------------------------
    def bell_fd(self) -> int:
        """The fd a parked reader selects/polls on (-1 = none)."""
        return self._bell_rx_fd

    def park(self) -> bool:
        """Arm the parked flag; False when frames raced in (drain now)."""
        return self.rx.reader_park()

    def unpark(self) -> None:
        self.rx.reader_unpark()
        if self._bell_rx_fd >= 0:
            try:  # drain accumulated doorbell bytes
                while os.read(self._bell_rx_fd, 4096):
                    pass
            except OSError:
                pass

    def close(self, unlink: bool = False) -> None:
        for fd in (self._bell_rx_fd, self._bell_tx_fd):
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self._bell_rx_fd = self._bell_tx_fd = -1
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        if unlink:
            for p in (self.path, *self.bell_paths(self.path)):
                try:
                    os.unlink(p)
                except OSError:
                    pass


def _map_bytes(slot_size: int, nslots: int) -> int:
    stride = _round_up(4 + slot_size, 64)
    return HEADER_BYTES + 2 * nslots * stride


def create_lane(path, slot_size: int = DEFAULT_SLOT_SIZE,
                nslots: int = DEFAULT_NSLOTS) -> ShmLane:
    """Client side: build (or rebuild) the lane file + doorbell FIFOs.

    A leftover file whose creator pid is dead is RECLAIMED — unlinked and
    rebuilt — so a SIGKILLed client's next incarnation negotiates a clean
    lane instead of inheriting mid-stream cursors (the stale-ring test).
    A live creator's file is also replaced: lane names are per-peer-id,
    so a same-name rebuild means a reconnect of the same logical client.
    """
    if nslots & (nslots - 1):
        raise ValueError(f"nslots {nslots} not a power of two")
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    for p in (path, *ShmLane.bell_paths(path)):
        try:
            os.unlink(p)
        except OSError:
            pass
    for bell in ShmLane.bell_paths(path):
        os.mkfifo(bell, 0o600)
    size = _map_bytes(slot_size, nslots)
    fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
    try:
        os.ftruncate(fd, size)
        mm = mmap.mmap(fd, size)
    finally:
        os.close(fd)
    _U32.pack_into(mm, _OFF_MAGIC, MAGIC)
    struct.pack_into("<HH", mm, _OFF_VERSION, VERSION, 0)
    _U32.pack_into(mm, _OFF_SLOT_SIZE, slot_size)
    _U32.pack_into(mm, _OFF_NSLOTS, nslots)
    _U32.pack_into(mm, _OFF_CREATOR_PID, os.getpid())
    return ShmLane(path, "client", mm, slot_size, nslots)


class LaneError(ValueError):
    """Unattachable lane file (bad magic/version/geometry)."""


def attach_lane(path) -> ShmLane:
    """Hub side: map a client-created lane after validating its header.
    Raises :class:`LaneError` on anything that isn't a well-formed,
    current-version lane of sane geometry (the handshake-fuzz contract:
    a malformed offer must never crash or half-attach the hub)."""
    path = Path(path)
    st = os.stat(path)
    if not stat.S_ISREG(st.st_mode):
        raise LaneError(f"lane {path} is not a regular file")
    if st.st_size < HEADER_BYTES:
        raise LaneError(f"lane {path} too short ({st.st_size} B)")
    fd = os.open(path, os.O_RDWR)
    try:
        mm = mmap.mmap(fd, st.st_size)
    finally:
        os.close(fd)
    try:
        (magic,) = _U32.unpack_from(mm, _OFF_MAGIC)
        if magic != MAGIC:
            raise LaneError(f"bad lane magic 0x{magic:08x}")
        (version,) = struct.unpack_from("<H", mm, _OFF_VERSION)
        if version != VERSION:
            raise LaneError(f"unsupported lane version {version}")
        (slot_size,) = _U32.unpack_from(mm, _OFF_SLOT_SIZE)
        (nslots,) = _U32.unpack_from(mm, _OFF_NSLOTS)
        if not (0 < slot_size <= 1 << 20):
            raise LaneError(f"bad slot_size {slot_size}")
        if not (0 < nslots <= 1 << 16) or nslots & (nslots - 1):
            raise LaneError(f"bad nslots {nslots}")
        if st.st_size < _map_bytes(slot_size, nslots):
            raise LaneError(f"lane {path} shorter than its geometry")
    except LaneError:
        mm.close()
        raise
    lane = ShmLane(path, "hub", mm, slot_size, nslots)
    lane.mark_attached(os.getpid())
    return lane


def reclaim_stale(dir_path: Optional[Path] = None) -> List[Path]:
    """Sweep ``dir_path`` (default: the lane dir) for lane files whose
    creator is dead and unlink them (plus their bells).  Returns the
    reclaimed paths — buspool calls this at spawn so a crashed fleet's
    litter never accumulates."""
    d = Path(dir_path) if dir_path is not None else lane_dir()
    reclaimed: List[Path] = []
    if not d.is_dir():
        return reclaimed
    for p in sorted(d.glob("*.shl")):
        try:
            with open(p, "rb") as f:
                head = f.read(HEADER_BYTES)
            if len(head) < 24:
                continue
            (magic,) = _U32.unpack_from(head, _OFF_MAGIC)
            if magic != MAGIC:
                continue
            (pid,) = _U32.unpack_from(head, _OFF_CREATOR_PID)
            if _pid_alive(pid):
                continue
        except OSError:
            continue
        for q in (p, *ShmLane.bell_paths(p)):
            try:
                os.unlink(q)
            except OSError:
                pass
        reclaimed.append(p)
    return reclaimed


def lane_path_for(peer_id: str, shard: int,
                  dir_path: Optional[Path] = None) -> Path:
    """Canonical lane file path for a (peer, busd-shard) pair.  Peer ids
    are sanitized to a filename-safe alphabet (they're alnum in practice:
    "py-…", "12D3KooW…")."""
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in peer_id)[:80]
    d = Path(dir_path) if dir_path is not None else lane_dir()
    return d / f"{safe}-s{shard}.shl"
