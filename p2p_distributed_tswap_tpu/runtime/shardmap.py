"""Deterministic topic→shard map for the federated bus pool (ISSUE 6).

One busd hub is the fleet's throughput ceiling and single point of
failure; the reference runs a libp2p gossipsub *mesh* (PAPER.md L2), not
a hub.  The production rebuild shards the bus itself: ``JG_BUS_SHARDS``
busd processes, each owning a deterministic slice of the topic space
(native mirror: ``cpp/common/shardmap.hpp``, kept choice-identical and
golden-tested via ``cpp/probes/codec_golden.cpp --shardmap``).

Ownership rules — every topic is owned by EXACTLY ONE shard:

- region position topics ``mapd.pos.<rx>.<ry>`` (runtime/region.py)
  spread across ALL shards by the region indices:
  ``(rx * 7919 + ry * 104729) % n`` — deterministic from the region
  math alone, so py and cpp clients and every busd agree without any
  coordination;
- a position topic whose suffix is not two decimal ints falls back to
  FNV-1a over the full topic string (still deterministic, still one
  owner);
- everything else — the control plane: ``mapd``, ``mapd.path``,
  ``mapd.metrics``, the ``solver`` plan wire, discovery — lives on the
  designated HOME shard (index 0) and reaches the other shards over
  busd↔busd peering links.

Subscriptions map to the set of shards that may own a matching topic:
an exact topic maps to its single owner; a wildcard (``.*`` suffix,
busd prefix matching) that can match region position topics spans ALL
shards (the wildcard subscriber opens a connection per shard); any
other wildcard stays on the home shard.

``JG_BUS_SHARDS=1`` (the default) is the kill switch: everything maps
to shard 0 and both BusClients keep today's single-hub wire verbatim.

Tenant namespaces (ISSUE 8): a namespaced wire topic ``<ns>:<topic>``
(runtime/busns.py) is classified by its LOGICAL topic — a tenant's
region beacons spread across the pool and its wildcards span shards
exactly like the un-namespaced fleet's — while the FNV fallback hashes
the full wire topic, so two tenants' odd-suffix position topics still
land on (deterministically) independent shards.
"""

from __future__ import annotations

import os
from typing import List

from p2p_distributed_tswap_tpu.runtime import busns
from p2p_distributed_tswap_tpu.runtime.region import POS_TOPIC_PREFIX

HOME_SHARD = 0
SHARD_PORTS_ENV = "JG_BUS_SHARD_PORTS"
NUM_SHARDS_ENV = "JG_BUS_SHARDS"


def fnv1a32(s: str) -> int:
    """FNV-1a over the UTF-8 bytes of ``s`` (32-bit) — the fallback hash
    for position topics with a non-numeric suffix; byte-identical to the
    C++ mirror."""
    h = 2166136261
    for b in s.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def _ascii_digits(s: str) -> bool:
    """ASCII decimal digits only — mirrors the C++ ``all_digits``.
    Python's ``str.isdigit`` alone accepts Unicode digit-likes ('³')
    that ``int()`` rejects or (Arabic-Indic digits) that C++ would send
    down the FNV path: either a crash or a routing divergence."""
    return bool(s) and s.isascii() and s.isdigit()


def shard_of(topic: str, num_shards: int) -> int:
    """The single owning shard of ``topic`` in an ``num_shards`` pool.
    ``topic`` may be a namespaced wire topic (``<ns>:<topic>``): the
    logical topic decides the class, the full wire topic feeds the FNV
    fallback."""
    if num_shards <= 1:
        return HOME_SHARD
    logical = busns.strip_ns(topic)
    if logical.startswith(POS_TOPIC_PREFIX) and not logical.endswith("*"):
        suffix = logical[len(POS_TOPIC_PREFIX):]
        rx, dot, ry = suffix.partition(".")
        if dot and _ascii_digits(rx) and _ascii_digits(ry):
            # the region math IS the shard map: deterministic from the
            # region indices, no per-topic state anywhere
            return (int(rx) * 7919 + int(ry) * 104729) % num_shards
        return fnv1a32(topic) % num_shards
    return HOME_SHARD


def shards_for_subscription(topic: str, num_shards: int) -> List[int]:
    """Every shard a subscription to ``topic`` must reach: the owner for
    an exact topic; ALL shards for a wildcard that can match region
    position topics; the home shard otherwise."""
    if num_shards <= 1:
        return [HOME_SHARD]
    if topic.endswith(".*"):
        prefix = busns.strip_ns(topic)[:-1]  # busd matches by this prefix
        # a wildcard spans shards iff some "mapd.pos.…" topic can match
        # it: its prefix extends POS_TOPIC_PREFIX or is a prefix of it
        if prefix.startswith(POS_TOPIC_PREFIX) \
                or POS_TOPIC_PREFIX.startswith(prefix):
            return list(range(num_shards))
        return [HOME_SHARD]
    return [shard_of(topic, num_shards)]


def parse_shard_ports(spec: str) -> List[int]:
    """Parse a ``JG_BUS_SHARD_PORTS`` value ("7450,7451,7452") into the
    ordered shard port list (index = shard id).  Bad entries raise —
    a half-parsed pool map must never route silently."""
    ports = [int(p) for p in spec.split(",") if p.strip()]
    if not ports:
        raise ValueError(f"empty shard port list: {spec!r}")
    if any(p < 1 or p > 65535 for p in ports):
        raise ValueError(f"shard port out of range: {spec!r}")
    return ports


def shard_ports_from_env(default_port: int) -> List[int]:
    """The shard port list the environment advertises, else the single
    ``default_port`` (legacy single-hub wire)."""
    spec = os.environ.get(SHARD_PORTS_ENV, "")
    if spec.strip():
        return parse_shard_ports(spec)
    return [default_port]
