"""Process-spawn fleet runner.

The working equivalent of the reference's bit-rotted ``task_test`` harness
(src/test/run/task.rs:32-284, which spawns binary names that no longer exist
— SURVEY C12): launches bus + manager + N agents as OS processes, forwards
operator commands to the manager's stdin, and kills the whole fleet on exit.

Library use (integration tests) and CLI:
    python -m p2p_distributed_tswap_tpu.runtime.fleet \
        --mode decentralized --agents 3 --duration 30
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from datetime import datetime
from pathlib import Path
from typing import Dict, List, Optional

from p2p_distributed_tswap_tpu.core.config import RuntimeConfig
from p2p_distributed_tswap_tpu.obs import trace
from p2p_distributed_tswap_tpu.runtime import buspool
from p2p_distributed_tswap_tpu.runtime import region as regionlib
from p2p_distributed_tswap_tpu.runtime import shmlane

REPO_ROOT = Path(__file__).resolve().parents[2]
BUILD_DIR = REPO_ROOT / "cpp" / "build"


def ensure_built() -> Path:
    """Build the C++ runtime if needed; returns the build dir."""
    if not (BUILD_DIR / "mapd_bus").exists():
        subprocess.run(["cmake", "-S", str(REPO_ROOT / "cpp"), "-B",
                        str(BUILD_DIR), "-G", "Ninja"], check=True,
                       capture_output=True)
        subprocess.run(["ninja", "-C", str(BUILD_DIR)], check=True,
                       capture_output=True)
    return BUILD_DIR


def wait_for_log(path, needle: str, timeout_s: float,
                 proc: Optional[subprocess.Popen] = None,
                 tail_bytes: int = 65536) -> bool:
    """Poll a child's log file for a readiness banner, reading only the
    TAIL (a --warm solverd log grows; re-reading it whole 2x/s is wasted
    I/O).  True on match; False on timeout or — when ``proc`` is given —
    on the child exiting first.  Shared by the fleet runner and the
    harnesses (solver_crossover, fleetsim), which each had their own
    copy of this loop before."""
    deadline = time.monotonic() + timeout_s
    path = Path(path)
    needle_b = needle.encode()
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return False
        if path.exists():
            with open(path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - tail_bytes))
                if needle_b in f.read():
                    return True
        time.sleep(0.5)
    return False


def build_single_tu(binary_name: str, source_rel: str) -> Optional[Path]:
    """Build one single-translation-unit runtime binary with a bare g++
    (every cpp/ binary is one TU, so no cmake/ninja needed) — the shared
    helper behind the codec-golden / busd test-and-smoke builders.
    Returns the binary path, or None when it neither exists nor can be
    built (no C++ toolchain)."""
    import shutil

    binary = BUILD_DIR / binary_name
    if binary.exists():
        return binary
    gxx = shutil.which("g++")
    if gxx is None:
        return None
    BUILD_DIR.mkdir(parents=True, exist_ok=True)
    subprocess.run([gxx, "-O2", "-std=c++17", "-Icpp", source_rel,
                    "-o", str(binary)], cwd=str(REPO_ROOT), check=True,
                   capture_output=True)
    return binary


class Fleet:
    """A managed fleet of runtime processes (killed on close/GC)."""

    def __init__(self, mode: str = "decentralized", num_agents: int = 3,
                 port: int = 7450, map_file: Optional[str] = None,
                 solver: str = "cpu", log_dir: Optional[str] = None,
                 env: Optional[dict] = None,
                 config: Optional[RuntimeConfig] = None,
                 solverd_args: Optional[List[str]] = None,
                 bus_shards: Optional[int] = None,
                 bus_cpu_affinity: Optional[str] = None,
                 regions: Optional[str] = None,
                 ha: Optional[bool] = None):
        assert mode in ("centralized", "decentralized")
        # federated world regions (ISSUE 14): a "CxR" spec brings up one
        # (manager [, solverd]) pair PER REGION on the shared bus pool —
        # region i's manager owns the i-th rectangle (--region-id), its
        # plan wire is solver.r<i>, audit pairing ns r<i>.  None/"1"
        # keeps today's single-pair fleet byte-identical.
        fed_cols, fed_rows = regionlib.fed_parse_spec(regions)
        fed_total = fed_cols * fed_rows
        build = ensure_built()
        self.procs: List[subprocess.Popen] = []
        self._names: List[str] = []
        # Child stderr is never dropped: with no explicit log_dir each run
        # gets a fresh timestamped directory, so a crashing child's last
        # words (and its exit code, see exit_summary) survive the fleet
        # teardown instead of vanishing into DEVNULL.
        if log_dir is None:
            stamp = (datetime.now().strftime("%Y%m%d-%H%M%S")
                     + f"-{os.getpid()}")
            log_dir = REPO_ROOT / "results" / "fleet_logs" / stamp
        self.log_dir = Path(log_dir)
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self.exit_summary: List[Dict] = []
        penv = dict(os.environ)
        # flight-recorder dumps (always-on black box, obs/flightrec.py)
        # land next to the per-process logs unless the caller routed them
        # elsewhere — so a fleet incident leaves logs AND rings together
        penv.setdefault("JG_FLIGHT_DIR", str(self.log_dir))
        # zero-copy bus lanes (ISSUE 18): when JG_BUS_SHM is on, the
        # fleet's ring files live under the run dir with its logs — one
        # sweep cleans a run, and two concurrent fleets never collide on
        # the default /tmp lane dir
        penv.setdefault(shmlane.SHM_DIR_ENV,
                        str(self.log_dir / "shm_lanes"))
        if config is not None:
            # one RuntimeConfig configures every binary in the fleet
            # (MAPD_* env knobs, cpp/common/knobs.hpp)
            penv.update(config.to_env())
        if env:
            penv.update(env)
        self._logs: List = []

        def spawn(name, cmd, stdin=None):
            out = open(self.log_dir / f"{name}.log", "w")
            self._logs.append(out)
            p = subprocess.Popen(cmd, stdin=stdin, stdout=out,
                                 stderr=subprocess.STDOUT, env=penv)
            self.procs.append(p)
            self._names.append(name)
            trace.instant("fleet.spawn", proc=name, pid=p.pid)
            return p

        map_args = ["--map", map_file] if map_file else []
        # Sharded bus pool (ISSUE 6): JG_BUS_SHARDS (or the bus_shards
        # arg) spawns that many federated busd shards — shard 0 keeps
        # `port` so external tools (fleet_top, harness watchers) reach
        # the control plane at the advertised address, and every child
        # gets JG_BUS_SHARD_PORTS so its BusClient routes per shard.
        # The default (1) is today's single hub, byte-identical.
        shards = int(bus_shards if bus_shards is not None
                     else (env or {}).get("JG_BUS_SHARDS")
                     or os.environ.get("JG_BUS_SHARDS", "1") or 1)
        # optional per-shard CPU pinning (buspool.parse_cpu_affinity spec;
        # JG_BUS_CPU_AFFINITY env for harnesses that configure via env)
        affinity = (bus_cpu_affinity if bus_cpu_affinity is not None
                    else (env or {}).get("JG_BUS_CPU_AFFINITY")
                    or os.environ.get("JG_BUS_CPU_AFFINITY", ""))
        self.bus_pool = buspool.BusPool(
            build / "mapd_bus", num_shards=max(1, shards), home_port=port,
            spawn=lambda name, cmd: spawn(name, cmd), settle_s=0.0,
            cpu_affinity=affinity)
        # THIS pool is the children's bus — a stale JG_BUS_SHARD_PORTS
        # inherited from the operator's shell (a previous manual pool)
        # must never leak into a fresh fleet
        penv.pop(buspool.SHARD_PORTS_ENV, None)
        penv.update(self.bus_pool.env())
        time.sleep(0.3)
        if mode == "centralized" and solver == "tpu":
            # --solver=tpu planning happens in the JAX solver daemon —
            # one per region in a federated fleet, each on its own
            # plan-wire topic
            for rid in range(fed_total):
                tag = f"_r{rid}" if fed_total > 1 else ""
                fed_args = regionlib.fed_cli_args(rid, fed_cols, fed_rows,
                                                  "solverd")
                sd_proc = spawn(f"solverd{tag}",
                                [sys.executable, "-m",
                                 "p2p_distributed_tswap_tpu.runtime"
                                 ".solverd",
                                 "--port", str(port), *map_args,
                                 *fed_args, *(solverd_args or [])])
                # wait for the readiness banner (printed AFTER any
                # --warm pre-compile) so the manager never opens with a
                # failover window; a startup death just means the
                # manager plans natively; without logs fall back to a
                # fixed headroom sleep
                if self.log_dir:
                    wait_for_log(self.log_dir / f"solverd{tag}.log",
                                 "solverd up", 240, proc=sd_proc)
                else:
                    time.sleep(8)  # accelerator init headroom
        # control-plane HA (ISSUE 15): ha=True (or JG_HA=1 in the
        # fleet env) pairs every region's manager with a warm standby
        # that tails its ledger1 replication stream and takes over on
        # lease expiry.  centralized-mode only — the decentralized
        # manager is not a replication source (yet).
        if ha is None:
            ha_env = str((env or {}).get("JG_HA")
                         or os.environ.get("JG_HA", ""))
            ha = ha_env not in ("", "0")
        ha_on = bool(ha) and mode == "centralized"
        self.managers: List[subprocess.Popen] = []
        self.standbys: List[Optional[subprocess.Popen]] = []
        for rid in range(fed_total):
            tag = f"_r{rid}" if fed_total > 1 else ""
            mgr_cmd = [str(build / f"mapd_manager_{mode}"),
                       "--port", str(port), *map_args]
            if mode == "centralized":
                mgr_cmd += ["--solver", solver]
            mgr_cmd += regionlib.fed_cli_args(rid, fed_cols, fed_rows,
                                              "manager")
            if ha_on:
                mgr_cmd += ["--ha", "1"]
            self.managers.append(spawn(f"manager{tag}", mgr_cmd,
                                       stdin=subprocess.PIPE))
            self.standbys.append(
                spawn(f"standby{tag}", mgr_cmd + ["--standby"],
                      stdin=subprocess.PIPE) if ha_on else None)
        self.manager = self.managers[0]
        time.sleep(0.3)
        for i in range(1, num_agents + 1):
            spawn(f"agent_{i}",
                  [str(build / f"mapd_agent_{mode}"), "--port", str(port),
                   "--seed", str(i), *map_args])
            time.sleep(0.1)
        self.port = port

    def command(self, line: str) -> None:
        """Send an operator CLI line to the manager (task | tasks N | ...)."""
        assert self.manager.stdin is not None
        self.manager.stdin.write((line + "\n").encode())
        self.manager.stdin.flush()

    def command_region(self, rid: int, line: str) -> None:
        """Send an operator CLI line to region ``rid``'s manager."""
        mgr = self.managers[rid]
        assert mgr.stdin is not None
        mgr.stdin.write((line + "\n").encode())
        mgr.stdin.flush()

    def quit(self, timeout: float = 10.0) -> None:
        try:
            self.command("quit")
            self.manager.wait(timeout=timeout)
        except Exception:
            pass
        self.close()

    def close(self) -> None:
        if not self.procs:
            return  # already closed; keep the recorded exit_summary
        # Children already dead BEFORE the teardown SIGTERM died on their
        # own — their exit codes are the fleet's failure record, not an
        # artifact of shutdown.
        died_early = {id(p): p.poll() for p in self.procs
                      if p.poll() is not None}
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                try:  # reap, or the summary below reads returncode None
                    p.wait(timeout=1)
                except subprocess.TimeoutExpired:
                    pass
        self.exit_summary = []
        for name, p in zip(self._names, self.procs):
            rec = {"proc": name, "pid": p.pid, "returncode": p.poll(),
                   "died_early": id(p) in died_early,
                   "log": str(self.log_dir / f"{name}.log")}
            self.exit_summary.append(rec)
            trace.instant("fleet.exit", proc=name, pid=p.pid,
                          returncode=rec["returncode"],
                          died_early=rec["died_early"])
            if rec["died_early"] and rec["returncode"] != 0:
                print(f"⚠️  fleet: {name} (pid {p.pid}) exited "
                      f"{rec['returncode']} before shutdown — see "
                      f"{rec['log']}", file=sys.stderr, flush=True)
        self.procs.clear()
        self._names.clear()
        for f in self._logs:
            try:
                f.close()
            except OSError:
                pass
        self._logs.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="decentralized",
                    choices=["centralized", "decentralized"])
    ap.add_argument("--agents", type=int, default=3)
    ap.add_argument("--duration", type=int, default=30)
    ap.add_argument("--port", type=int, default=7450)
    ap.add_argument("--map", default=None)
    ap.add_argument("--solver", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--tasks-every", type=float, default=3.0)
    ap.add_argument("--log-dir", default="results/fleet")
    args = ap.parse_args(argv)

    with Fleet(args.mode, args.agents, args.port, args.map, args.solver,
               args.log_dir) as fleet:
        print(f"fleet up: {args.mode}, {args.agents} agents, "
              f"bus port {args.port}; logs in {fleet.log_dir}")
        print(f"   live view: python analysis/fleet_top.py "
              f"--port {args.port}   (beacons on bus topic mapd.metrics)")
        time.sleep(3 + args.agents * 0.2)
        end = time.monotonic() + args.duration
        while time.monotonic() < end:
            fleet.command(f"tasks {args.agents}")
            time.sleep(args.tasks_every)
        fleet.command("metrics")
        time.sleep(1)
        fleet.quit()
        bad = [r for r in fleet.exit_summary
               if r["died_early"] and r["returncode"] != 0]
        for r in bad:
            print(f"fleet: {r['proc']} exited {r['returncode']} "
                  f"(log: {r['log']})")
    trace.flush()
    print("fleet shut down" + (f" ({len(bad)} child failure(s))"
                               if bad else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
