"""Region-sharded position-gossip topic math (ISSUE 4 tentpole).

The reference's scalability post-mortem proposes — but never builds —
geographic topic partitioning (DECENTRALIZED_ISSUES.md:62-96) to break the
O(N²) position broadcast.  This module is the Python half of that design
(native mirror: ``cpp/common/region.hpp``, kept rule-identical):

- the grid is partitioned into square regions of ``JG_REGION_CELLS``
  cells per edge (default 32);
- an agent publishes its position beacon on topic
  ``mapd.pos.<rx>.<ry>`` for the region containing its cell;
- a consumer interested in everything within Manhattan radius ``r`` of a
  cell subscribes to the ``(2k+1) x (2k+1)`` region neighborhood with
  ``k = ceil(r / region_cells)`` (clamped to the grid), re-subscribing
  when it crosses a region border.

Coverage guarantee (property-tested in tests/test_region_bus.py): for any
two cells within Manhattan distance ``r`` of each other, the publisher's
region topic is inside the subscriber's neighborhood — per-axis distance
``<= r`` implies region-index distance ``<= ceil(r / cells) = k``.

Managers (and other global consumers) subscribe the wildcard
``mapd.pos.*`` — busd matches topics ending in ``.*`` by prefix.
"""

from __future__ import annotations

from typing import List

POS_TOPIC_PREFIX = "mapd.pos."
POS_TOPIC_WILDCARD = "mapd.pos.*"
DEFAULT_REGION_CELLS = 32


def topic_for(x: int, y: int, cells: int) -> str:
    """Region topic of grid cell ``(x, y)``."""
    return f"{POS_TOPIC_PREFIX}{x // cells}.{y // cells}"


def neighborhood_topics(x: int, y: int, radius: int, cells: int,
                        width: int, height: int) -> List[str]:
    """Region topics covering everything within Manhattan ``radius`` of
    ``(x, y)``, clamped to the grid; sorted for determinism."""
    k = max(1, -(-radius // cells))  # ceil div, never less than 3x3
    rx, ry = x // cells, y // cells
    nrx = (width + cells - 1) // cells
    nry = (height + cells - 1) // cells
    out = []
    for gy in range(max(0, ry - k), min(nry - 1, ry + k) + 1):
        for gx in range(max(0, rx - k), min(nrx - 1, rx + k) + 1):
            out.append(f"{POS_TOPIC_PREFIX}{gx}.{gy}")
    return out
