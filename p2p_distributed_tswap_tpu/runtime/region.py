"""Region-sharded position-gossip topic math (ISSUE 4 tentpole).

The reference's scalability post-mortem proposes — but never builds —
geographic topic partitioning (DECENTRALIZED_ISSUES.md:62-96) to break the
O(N²) position broadcast.  This module is the Python half of that design
(native mirror: ``cpp/common/region.hpp``, kept rule-identical):

- the grid is partitioned into square regions of ``JG_REGION_CELLS``
  cells per edge (default 32);
- an agent publishes its position beacon on topic
  ``mapd.pos.<rx>.<ry>`` for the region containing its cell;
- a consumer interested in everything within Manhattan radius ``r`` of a
  cell subscribes to the ``(2k+1) x (2k+1)`` region neighborhood with
  ``k = ceil(r / region_cells)`` (clamped to the grid), re-subscribing
  when it crosses a region border.

Coverage guarantee (property-tested in tests/test_region_bus.py): for any
two cells within Manhattan distance ``r`` of each other, the publisher's
region topic is inside the subscriber's neighborhood — per-axis distance
``<= r`` implies region-index distance ``<= ceil(r / cells) = k``.

Managers (and other global consumers) subscribe the wildcard
``mapd.pos.*`` — busd matches topics ending in ``.*`` by prefix.
"""

from __future__ import annotations

from typing import List, Tuple

POS_TOPIC_PREFIX = "mapd.pos."
POS_TOPIC_WILDCARD = "mapd.pos.*"
DEFAULT_REGION_CELLS = 32

# ---------------------------------------------------------------------------
# Federated world regions (ISSUE 14) — the OWNERSHIP canon.
#
# Gossip regions above shard the position-beacon *topic space*; federation
# regions shard the *world itself*: the grid splits into a COLSxROWS grid of
# rectangles, each owned by its own (manager, solverd) pair.  This module is
# the single source of truth for that partition (native mirror:
# cpp/common/region.hpp FedMap, kept rule-identical and golden-tested via
# codec_golden --fedmap, the same discipline as runtime/shardmap.py):
#
# - spec "CxR" = C columns x R rows ("2x1" = two side-by-side regions);
#   a bare "N" means Nx1; "1"/"1x1"/unset = federation OFF (single manager,
#   wire byte-identical);
# - rectangles are ceil-width slabs: column c covers
#   [c*cw, min((c+1)*cw, width)) with cw = ceil(width/cols) (last column
#   may be narrower) — chosen over balanced splits because one integer
#   division decides ownership identically in py and cpp;
# - region id = ry * cols + rx (row-major);
# - assignment is deterministic from the id alone: manager index = solverd
#   index = region id, bus shard = region id mod pool size — no registry,
#   no coordination, every process derives the same map;
# - HYSTERESIS: an agent owned by region A is handed off only once its
#   position sits MORE than `margin` cells outside A's rectangle on some
#   axis (fed_escaped) — an agent oscillating on the border stays owned
#   (the ping-pong guard, tested in tests/test_federation.py);
# - the manager-to-manager handoff wire rides bus topic
#   "mapd.fed.<region>" (control plane -> HOME shard, like "solver"), and
#   each region pair's plan wire is "solver.r<region>" so N planning
#   planes share one bus pool without cross-talk.
# ---------------------------------------------------------------------------

FED_TOPIC_PREFIX = "mapd.fed."
DEFAULT_FED_HYSTERESIS = 2
DEFAULT_FED_BORDER = 2


def fed_parse_spec(spec) -> Tuple[int, int]:
    """``(cols, rows)`` from a federation spec: ``"CxR"`` or a bare
    ``"N"`` (= Nx1).  None/''/'1'/'1x1' = (1, 1) = federation off.
    Malformed specs raise — a half-parsed world partition must never
    route silently."""
    if spec is None:
        return (1, 1)
    s = str(spec).strip().lower()
    if s in ("", "0", "1", "1x1"):
        return (1, 1)
    parts = s.split("x")
    try:
        if len(parts) == 1:
            cols, rows = int(parts[0]), 1
        elif len(parts) == 2:
            cols, rows = int(parts[0]), int(parts[1])
        else:
            raise ValueError
    except ValueError:
        raise ValueError(f"bad federation spec {spec!r} (want N or CxR)")
    if cols < 1 or rows < 1:
        raise ValueError(f"bad federation spec {spec!r} (want N or CxR)")
    return (cols, rows)


def _slab(extent: int, n: int) -> int:
    """Ceil-division slab width: one integer op, identical in cpp."""
    return (extent + n - 1) // n


def fed_region_of(x: int, y: int, cols: int, rows: int,
                  width: int, height: int) -> int:
    """Region id owning grid cell ``(x, y)`` (row-major ry*cols+rx)."""
    cw, rh = _slab(width, cols), _slab(height, rows)
    rx = min(x // cw, cols - 1)
    ry = min(y // rh, rows - 1)
    return ry * cols + rx


def fed_rect(rid: int, cols: int, rows: int, width: int,
             height: int) -> Tuple[int, int, int, int]:
    """Half-open rectangle ``(x0, y0, x1, y1)`` of region ``rid``."""
    cw, rh = _slab(width, cols), _slab(height, rows)
    rx, ry = rid % cols, rid // cols
    return (rx * cw, ry * rh,
            min((rx + 1) * cw, width), min((ry + 1) * rh, height))


def fed_escaped(x: int, y: int, rect: Tuple[int, int, int, int],
                margin: int) -> bool:
    """True once ``(x, y)`` sits MORE than ``margin`` cells outside
    ``rect`` on either axis — the handoff trigger.  margin >= 1 is the
    border-ping-pong hysteresis: a cell just across the line does not
    escape."""
    x0, y0, x1, y1 = rect
    return (x < x0 - margin or x > x1 - 1 + margin
            or y < y0 - margin or y > y1 - 1 + margin)


def fed_in_border(x: int, y: int, rect: Tuple[int, int, int, int],
                  border: int) -> bool:
    """True for a cell OUTSIDE ``rect`` but within ``border`` cells of
    it on both axes — the strip whose foreign agents are mirrored into
    this region's plans as stationary lanes (boundary planning
    correctness: TSWAP routes around them instead of planning two
    regions' agents into one border cell)."""
    x0, y0, x1, y1 = rect
    if x0 <= x < x1 and y0 <= y < y1:
        return False  # inside: owned, not mirrored
    return (x0 - border <= x <= x1 - 1 + border
            and y0 - border <= y <= y1 - 1 + border)


def fed_assignment(rid: int, cols: int, rows: int,
                   num_shards: int) -> dict:
    """The deterministic region -> (manager, solverd, bus-shard)
    assignment: every process (and every test) derives the same fleet
    layout from the region id alone.

    ``bus_shard`` is a PLACEMENT HINT, not current routing: today the
    region's control topics (``mapd.fed.<id>``, ``solver.r<id>``) ride
    the HOME shard like every control-plane topic (runtime/shardmap.py)
    — what actually spreads across the pool with region count is the
    region's POSITION-GOSSIP load, because federated managers subscribe
    only their rect's ``mapd.pos.<rx>.<ry>`` topics and those shard by
    the region indices.  The hint records where a future shard-routing
    of the control topics would deterministically place them."""
    total = cols * rows
    if not 0 <= rid < total:
        raise ValueError(f"region {rid} out of range for {cols}x{rows}")
    return {"region": rid, "manager": rid, "solverd": rid,
            "bus_shard": rid % max(1, num_shards),
            "handoff_topic": fed_topic(rid),
            "solver_topic": fed_solver_topic(rid, total)}


def fed_cli_args(rid: int, cols: int, rows: int, role: str) -> list:
    """The per-region CLI flags every spawn site shares, derived from
    :func:`fed_assignment` — one place to change the topic scheme or
    add a per-region flag (fleet.py, fleetsim run_rung/run_replay and
    federation_smoke all spawn region pairs).  ``role``: "manager"
    (regions + id + audit ns + solver topic) or "solverd" (solver
    topic + audit ns).  Empty for a 1x1 world (the kill switch)."""
    total = cols * rows
    if total <= 1:
        return []
    a = fed_assignment(rid, cols, rows, 1)
    common = ["--solver-topic", a["solver_topic"], "--audit-ns", f"r{rid}"]
    if role == "solverd":
        return common
    if role == "manager":
        return ["--regions", f"{cols}x{rows}", "--region-id", str(rid),
                *common]
    raise ValueError(f"unknown federation role {role!r}")


def fed_topic(rid: int) -> str:
    """Manager-to-manager handoff topic of region ``rid`` (control
    plane: shardmap routes it to the HOME shard)."""
    return f"{FED_TOPIC_PREFIX}{rid}"


def fed_solver_topic(rid: int, total: int) -> str:
    """Region ``rid``'s plan-wire topic.  A single-region world keeps
    the legacy "solver" topic — byte-identical wire with federation
    off."""
    return "solver" if total <= 1 else f"solver.r{rid}"


def topic_for(x: int, y: int, cells: int) -> str:
    """Region topic of grid cell ``(x, y)``."""
    return f"{POS_TOPIC_PREFIX}{x // cells}.{y // cells}"


def neighborhood_topics(x: int, y: int, radius: int, cells: int,
                        width: int, height: int) -> List[str]:
    """Region topics covering everything within Manhattan ``radius`` of
    ``(x, y)``, clamped to the grid; sorted for determinism."""
    k = max(1, -(-radius // cells))  # ceil div, never less than 3x3
    rx, ry = x // cells, y // cells
    nrx = (width + cells - 1) // cells
    nry = (height + cells - 1) // cells
    out = []
    for gy in range(max(0, ry - k), min(nry - 1, ry + k) + 1):
        for gx in range(max(0, rx - k), min(nrx - 1, rx + k) + 1):
            out.append(f"{POS_TOPIC_PREFIX}{gx}.{gy}")
    return out
