"""Benchmark scenario ladder (BASELINE.json configs).

The reference's scale axes are agent count and grid size (SURVEY §5); these
are the configs the framework is benchmarked on, from the reference's comfort
zone (tens of agents, 100x100 empty grid) to three orders of magnitude beyond
(10k agents on a 1024^2 warehouse, 100k on 4096^2 sharded)."""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.core.sampling import start_positions_array
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    grid_fn: Callable[[], Grid]
    num_agents: int
    num_tasks: int
    replan_chunk: int = 64
    # None = centralized global view; 15 = the reference's decentralized
    # radius (src/bin/decentralized/agent.rs:796-801).  Same solver, masked
    # visibility inside the kernel — the TPU analog of the reference's
    # central experiment (compare_path_metrics.py:33-106).
    visibility_radius: int | None = None

    def build(self, seed: int = 0):
        grid = self.grid_fn()
        starts = start_positions_array(grid, self.num_agents, seed=seed)
        tasks = TaskGenerator(grid, seed=seed + 1).generate_task_arrays(
            self.num_tasks)
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=self.num_agents,
                           replan_chunk=min(self.replan_chunk, self.num_agents),
                           visibility_radius=self.visibility_radius)
        return grid, starts, tasks, cfg

    def decentralized(self, radius: int = 15) -> "Scenario":
        """The same configuration solved under the reference's radius-15
        local-view semantics (suffix ``-decent``)."""
        return dataclasses.replace(self, name=f"{self.name}-decent",
                                   visibility_radius=radius)


# BASELINE.json config ladder
REFERENCE_DEMO = Scenario(          # the reference's comfortable envelope
    "ref-50x100x100", Grid.default, 50, 50, replan_chunk=50)
SMALL = Scenario(
    "100a-256-obstacles", lambda: Grid.random_obstacles(256, 256, 0.1, seed=0),
    100, 100)
MEDIUM = Scenario(
    "1k-512", lambda: Grid.random_obstacles(512, 512, 0.1, seed=0), 1000, 1000,
    replan_chunk=128)
FLAGSHIP = Scenario(                # north-star config: 10k agents, 1024^2
    # replan_chunk 64: transient replan memory is O(chunk * H * W) int32 and
    # must fit beside the persistent 5.25 GB packed fields on a 16 GB chip.
    "10k-1024-warehouse", lambda: Grid.warehouse(1024, 1024), 10_000, 10_000,
    replan_chunk=64)
EXTREME = Scenario(                 # v5e-16 territory, agent-axis sharded
    "100k-4096", lambda: Grid.warehouse(4096, 4096), 100_000, 100_000,
    replan_chunk=512)
# EXTREME-lite: the 4096^2 grid axis on ONE chip at reduced agent count
# (VERDICT r2 missing item 3) — de-risks the EXTREME field working set
# before multi-chip hardware exists.  Memory: packed fields are
# HW/2 = 8 MB/agent at 4096^2, so 512 agents = 4 GB persistent — x2
# resident across undonated dispatches (both the host-driven prime burst,
# mapd.host_prime_fields, and the per-step loop) = 8 GB, leaving the
# (8, 4096^2) sweep transient ~2 GB of slack on a 16 GB chip.  The fused
# one-program prime at this grid reliably crashes the axon TPU worker;
# bench.py primes this rung host-side chunk by chunk.
EXTREME_LITE = Scenario(
    "512a-4096-warehouse", lambda: Grid.warehouse(4096, 4096), 512, 512,
    replan_chunk=8)

LADDER = [REFERENCE_DEMO, SMALL, MEDIUM, FLAGSHIP, EXTREME]

# Decentralized (radius-15) counterparts for the cent-vs-decent table —
# the reference's core experiment at TPU scale (VERDICT r2 missing item 2).
REFERENCE_DEMO_DECENT = REFERENCE_DEMO.decentralized()
MEDIUM_DECENT = MEDIUM.decentralized()
FLAGSHIP_DECENT = FLAGSHIP.decentralized()
