"""Benchmark scenario ladder (BASELINE.json configs).

The reference's scale axes are agent count and grid size (SURVEY §5); these
are the configs the framework is benchmarked on, from the reference's comfort
zone (tens of agents, 100x100 empty grid) to three orders of magnitude beyond
(10k agents on a 1024^2 warehouse, 100k on 4096^2 sharded)."""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from p2p_distributed_tswap_tpu.core.config import (
    SolverConfig,
    stale_knobs_active,
)
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.core.sampling import start_positions_array
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    grid_fn: Callable[[], Grid]
    num_agents: int
    num_tasks: int
    replan_chunk: int = 64
    # None = centralized global view; 15 = the reference's decentralized
    # radius (src/bin/decentralized/agent.rs:796-801).  Same solver, masked
    # visibility inside the kernel — the TPU analog of the reference's
    # central experiment (compare_path_metrics.py:33-106).
    visibility_radius: int | None = None
    # Stale/async decentralized semantics (SolverConfig docs; the
    # reference's actual decentralized reality): neighbor-view refresh
    # period, view TTL, swap-commit latency.
    view_refresh_steps: int = 1
    view_ttl_steps: int | None = None
    swap_commit_delay: int = 0
    # Horizon (ref tswap.rs:167 default 2000); stale rungs wait more
    # rounds and get headroom so divergence shows as a longer makespan,
    # not a failed certification.
    max_timesteps: int = 2000

    def build(self, seed: int = 0):
        grid = self.grid_fn()
        starts = start_positions_array(grid, self.num_agents, seed=seed)
        tasks = TaskGenerator(grid, seed=seed + 1).generate_task_arrays(
            self.num_tasks)
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=self.num_agents,
                           max_timesteps=self.max_timesteps,
                           replan_chunk=min(self.replan_chunk, self.num_agents),
                           visibility_radius=self.visibility_radius,
                           view_refresh_steps=self.view_refresh_steps,
                           view_ttl_steps=self.view_ttl_steps,
                           swap_commit_delay=self.swap_commit_delay)
        return grid, starts, tasks, cfg

    def decentralized(self, radius: int = 15) -> "Scenario":
        """The same configuration solved under the reference's radius-15
        local-view semantics, fresh-atomic variant (suffix ``-decent``)."""
        return dataclasses.replace(self, name=f"{self.name}-decent",
                                   visibility_radius=radius)

    def stale(self, radius: int = 15, refresh: int = 2,
              ttl: int | None = None, delay: int = 1,
              horizon_factor: int = 2) -> "Scenario":
        """The decentralized configuration under the reference's ACTUAL
        semantics: views refreshed every ``refresh`` steps on decoupled
        cadences (500 ms broadcast analog) and one-step non-atomic
        goal-swap commits (suffix ``-decent-stale``).

        ``ttl`` (the 10 s cache age-out analog) defaults to None here ON
        PURPOSE: in an offline solve every agent is alive and rebroadcasts
        within ``refresh`` steps, so no entry can ever age past the TTL —
        a ttl knob on these rungs would be dead config dressed up as
        coverage.  The TTL semantics matter when agents die or mute (the
        active-mask / host-runtime case) and are pinned by
        tests/test_stale_mode.py::test_ttl_expires_unrefreshed_entries."""
        return dataclasses.replace(
            self, name=f"{self.name}-decent-stale",
            visibility_radius=radius, view_refresh_steps=refresh,
            view_ttl_steps=ttl, swap_commit_delay=delay,
            max_timesteps=self.max_timesteps * horizon_factor)

    @property
    def mode(self) -> str:
        if self.visibility_radius is None:
            return "centralized"
        base = f"decentralized-r{self.visibility_radius}"
        if stale_knobs_active(self.visibility_radius,
                              self.view_refresh_steps,
                              self.view_ttl_steps, self.swap_commit_delay):
            return (f"{base}-stale(k={self.view_refresh_steps},"
                    f"ttl={self.view_ttl_steps},"
                    f"delay={self.swap_commit_delay})")
        return base


# BASELINE.json config ladder
REFERENCE_DEMO = Scenario(          # the reference's comfortable envelope
    "ref-50x100x100", Grid.default, 50, 50, replan_chunk=50)
SMALL = Scenario(
    "100a-256-obstacles", lambda: Grid.random_obstacles(256, 256, 0.1, seed=0),
    100, 100)
MEDIUM = Scenario(
    "1k-512", lambda: Grid.random_obstacles(512, 512, 0.1, seed=0), 1000, 1000,
    replan_chunk=128)
FLAGSHIP = Scenario(                # north-star config: 10k agents, 1024^2
    # replan_chunk 64: transient replan memory is O(chunk * H * W) int32 and
    # must fit beside the persistent 5.25 GB packed fields on a 16 GB chip.
    "10k-1024-warehouse", lambda: Grid.warehouse(1024, 1024), 10_000, 10_000,
    replan_chunk=64)
EXTREME = Scenario(                 # v5e-16 territory, agent-axis sharded
    "100k-4096", lambda: Grid.warehouse(4096, 4096), 100_000, 100_000,
    replan_chunk=512)
# EXTREME-lite: the 4096^2 grid axis on ONE chip at reduced agent count
# (VERDICT r2 missing item 3) — de-risks the EXTREME field working set
# before multi-chip hardware exists.  Memory: packed fields are
# HW/2 = 8 MB/agent at 4096^2, so 512 agents = 4 GB persistent — x2
# resident across undonated dispatches (both the host-driven prime burst,
# mapd.host_prime_fields, and the per-step loop) = 8 GB, leaving the
# (8, 4096^2) sweep transient ~2 GB of slack on a 16 GB chip.  The fused
# one-program prime at this grid reliably crashes the axon TPU worker;
# bench.py primes this rung host-side chunk by chunk.
EXTREME_LITE = Scenario(
    "512a-4096-warehouse", lambda: Grid.warehouse(4096, 4096), 512, 512,
    replan_chunk=8)
# EXTREME-lite with the horizon raised past the grid diameter (VERDICT r3
# missing item 3): at 4096^2 the default 2000-step horizon is below the
# shortest-path length of a typical task, so "completion" was undefined and
# no 4096^2 solve had ever been certified.  20k steps clears the ~8k
# diameter plus both journey legs with slack; record_paths stays off (the
# bench path certifies per-step invariants device-side instead).
EXTREME_LITE_FULL = dataclasses.replace(
    EXTREME_LITE, name="512a-4096-warehouse-full", max_timesteps=20_000)

LADDER = [REFERENCE_DEMO, SMALL, MEDIUM, FLAGSHIP, EXTREME]

# Decentralized (radius-15) counterparts for the cent-vs-decent table —
# the reference's core experiment at TPU scale (VERDICT r2 missing item 2).
REFERENCE_DEMO_DECENT = REFERENCE_DEMO.decentralized()
MEDIUM_DECENT = MEDIUM.decentralized()
FLAGSHIP_DECENT = FLAGSHIP.decentralized()

# Stale/async counterparts (VERDICT r3 missing item 1): the reference's
# decentralized agents act on views up to 10 s old and commit swaps
# non-atomically; these rungs carry that reality at TPU scale.
REFERENCE_DEMO_DECENT_STALE = REFERENCE_DEMO.stale()
MEDIUM_DECENT_STALE = MEDIUM.stale()
FLAGSHIP_DECENT_STALE = FLAGSHIP.stale()

# Congestion config (VERDICT r3 missing item 2): dense enough that the
# radius mask and staleness actually bite — the rung where centralized vs
# decentralized OUTCOMES diverge, not just step cost.  3k agents on a
# 256^2 warehouse ≈ 6% of free cells occupied (the flagship sits at ~1.3%).
CONGESTED = Scenario(
    "3k-256-congested", lambda: Grid.warehouse(256, 256), 3000, 3000,
    replan_chunk=64, max_timesteps=4000)
CONGESTED_DECENT = CONGESTED.decentralized()
CONGESTED_DECENT_STALE = CONGESTED.stale()
