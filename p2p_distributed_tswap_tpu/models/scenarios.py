"""Benchmark scenario ladder (BASELINE.json configs).

The reference's scale axes are agent count and grid size (SURVEY §5); these
are the configs the framework is benchmarked on, from the reference's comfort
zone (tens of agents, 100x100 empty grid) to three orders of magnitude beyond
(10k agents on a 1024^2 warehouse, 100k on 4096^2 sharded)."""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.core.sampling import start_positions_array
from p2p_distributed_tswap_tpu.core.tasks import TaskGenerator


@dataclasses.dataclass(frozen=True)
class Scenario:
    name: str
    grid_fn: Callable[[], Grid]
    num_agents: int
    num_tasks: int
    replan_chunk: int = 64

    def build(self, seed: int = 0):
        grid = self.grid_fn()
        starts = start_positions_array(grid, self.num_agents, seed=seed)
        tasks = TaskGenerator(grid, seed=seed + 1).generate_task_arrays(
            self.num_tasks)
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=self.num_agents,
                           replan_chunk=min(self.replan_chunk, self.num_agents))
        return grid, starts, tasks, cfg


# BASELINE.json config ladder
REFERENCE_DEMO = Scenario(          # the reference's comfortable envelope
    "ref-50x100x100", Grid.default, 50, 50, replan_chunk=50)
SMALL = Scenario(
    "100a-256-obstacles", lambda: Grid.random_obstacles(256, 256, 0.1, seed=0),
    100, 100)
MEDIUM = Scenario(
    "1k-512", lambda: Grid.random_obstacles(512, 512, 0.1, seed=0), 1000, 1000,
    replan_chunk=128)
FLAGSHIP = Scenario(                # north-star config: 10k agents, 1024^2
    # replan_chunk 64: transient replan memory is O(chunk * H * W) int32 and
    # must fit beside the persistent 5.25 GB packed fields on a 16 GB chip.
    "10k-1024-warehouse", lambda: Grid.warehouse(1024, 1024), 10_000, 10_000,
    replan_chunk=64)
EXTREME = Scenario(                 # v5e-16 territory, agent-axis sharded
    "100k-4096", lambda: Grid.warehouse(4096, 4096), 100_000, 100_000,
    replan_chunk=512)

LADDER = [REFERENCE_DEMO, SMALL, MEDIUM, FLAGSHIP, EXTREME]
