"""Batched parallel TSWAP step kernel.

A parallel-consistent reformulation of the reference's sequential
``tswap_step`` (src/algorithm/tswap.rs:174-286), per SURVEY §7 hard part 1.
All agents act at once on dense (N,) tensors; conflicts resolve with
deterministic lowest-agent-id priority.  Per-agent A* is gone: each agent's
next hop is one gather from its goal's **direction field** (see
``ops.distance``), and goal exchanges never recompute fields — they permute
the ``slot`` indirection that maps agents to field rows.

Step anatomy (one call = one timestep for all N agents):

1. **Goal-swapping phase**, ``swap_rounds`` rounds of:
   - Rule 3 (ref :197-202): agents blocked by a neighbor parked on its own
     goal swap goals with it.  Multiple claimants on one blocker resolve to
     the lowest agent id; applied as a gather permutation of (goal, slot).
   - Rule 4 (ref :204-249): deadlock cycles in the blocking graph
     ``f(i) = occupant of i's next hop`` are detected exactly up to
     ``cycle_cap`` length by iterated composition, and every cycle rotates
     goals "backward along the chain" simultaneously: goal/slot of ``x`` move
     to ``f(x)`` — again a pure permutation.
2. **Movement phase** (ref :257-285): mutual swaps (adjacent pairs that want
   each other's cells) exchange positions; remaining agents cascade into
   free-or-vacated cells over fixpoint rounds, lowest id winning contested
   cells.  The cascade preserves vertex-disjointness and never lets two
   agents cross an edge except via a mutual swap.

Documented divergences from the sequential reference (validated empirically
for makespan parity in tests):
- swaps/rotations resolve per parallel round, not interleaved per agent;
- an agent moves at most once per step (the reference's in-pass mutual swap
  can move the partner again later in the same pass, tswap.rs:269-278);
- the movement cascade lets an agent enter a cell vacated this step by ANY
  mover, where the sequential pass only sees vacancies created by
  lower-indexed agents — strictly more progress per step;
- **push extension** (deliberate fix of a reference deadlock): when the
  blocker is parked on the mover's OWN goal (two tasks sharing a delivery
  cell — goals equal, so the reference's Rule-3 swap exchanges identical
  values and no-ops forever, tswap.rs:197-202), the blocker's goal is
  retargeted to the mover's current cell; the next movement phase resolves
  the pair as a mutual position swap.  Pushed goals are served by the
  goal-adjacency shortcut below, so the blocker's (stale) field row is
  never consulted for them.

Next-hop lookups enforce Rule 1 explicitly (at-goal agents never move, even
if their field row is stale) and apply a **goal-adjacency shortcut**: an
agent whose goal is exactly one cell away steps straight to it, bypassing
its direction field.  For field-backed goals both are no-ops (the field
would say the same); together they make pushed/stale-row (goal, slot) pairs
— which Rule-3/4 exchanges may hand around — exact within one extra step
for movers and inert for parked agents.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.ops.distance import (
    apply_direction,
    gather_packed,
)


def next_hops(cfg: SolverConfig, dirs: jnp.ndarray, slot: jnp.ndarray,
              pos: jnp.ndarray) -> jnp.ndarray:
    """Desired next cell per agent: one byte gather from that agent's
    nibble-packed direction field (row ``slot[i]``; see
    ``ops.distance.pack_directions``).  Equals ``pos`` for stay (at
    goal/unreachable)."""
    code = gather_packed(dirs, slot, pos)
    return apply_direction(pos, code, cfg.width)


def _occupancy(cfg: SolverConfig, pos: jnp.ndarray,
               active: jnp.ndarray) -> jnp.ndarray:
    """(HW+1,) int32: agent id at each cell, -1 if empty.  Inactive agents
    scatter to the padded scratch cell and never occupy the grid."""
    n = cfg.num_agents
    return jnp.full(cfg.num_cells + 1, -1, jnp.int32).at[
        jnp.where(active, pos, cfg.num_cells)].set(
        jnp.arange(n, dtype=jnp.int32))


def _blockers(occ, pos, u):
    """Agent occupying each agent's desired next cell (-1 free / no move)."""
    has_move = u != pos
    return jnp.where(has_move, occ[u], -1), has_move


def _within_radius(cfg: SolverConfig, pos, i_idx, j_idx):
    """Manhattan-visibility mask for agent pairs (decentralized mode,
    ref TSWAP_RADIUS=15 at src/bin/decentralized/agent.rs:796-801).
    Centralized mode (visibility_radius=None) sees everyone."""
    if cfg.visibility_radius is None:
        return jnp.ones_like(i_idx, bool)
    w = cfg.width
    a, b = pos[i_idx], pos[j_idx]
    mh = (jnp.abs(a % w - b % w) + jnp.abs(a // w - b // w))
    return mh <= cfg.visibility_radius


def _apply_pair_swaps(goal, slot, sel, partner, n):
    """Permute (goal, slot) by the disjoint transpositions {i <-> partner[i]}
    for selected i.

    Scatters go through a padded scratch slot at index ``n`` instead of
    relying on mode="drop": XLA's CPU backend has been observed to *wrap*
    out-of-bounds scatter rows for some shapes instead of dropping them.
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    p = jnp.arange(n + 1, dtype=jnp.int32)
    p = p.at[jnp.where(sel, idx, n)].set(jnp.where(sel, partner, n))
    p = p.at[jnp.where(sel, partner, n)].set(jnp.where(sel, idx, n))
    p = p[:n]
    return goal[p], slot[p]


def _hops(cfg: SolverConfig, nh_fn, slot, pos, goal, active):
    """Next hops with Rule 1 and the goal-adjacency shortcut explicit.

    Rule 1 (at-goal agents never move, ref tswap.rs:186) is enforced here
    directly instead of relying on the field saying STAY at the goal: a
    pushed agent's field row targets its OLD goal, and without the explicit
    check a parked pushed agent would wander off its goal following the
    stale row.  Together with the adjacency shortcut this bounds any
    stale-row effect to one extra step for moving agents and zero for
    parked ones."""
    u = jnp.where(active, nh_fn(slot, pos), pos)
    w = cfg.width
    mh = jnp.abs(pos % w - goal % w) + jnp.abs(pos // w - goal // w)
    u = jnp.where(active & (mh == 1), goal, u)
    return jnp.where(pos == goal, pos, u)


def _swap_phase_round(cfg: SolverConfig, pos, goal, slot, pushed, nh_fn, occ,
                      active):
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)

    # ---- Rule 3: swap goals with a blocker parked on its own goal ----
    at_goal = pos == goal
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    bc = jnp.clip(b, 0, n - 1)
    cand = (has_move & (b >= 0) & at_goal[bc]
            & _within_radius(cfg, pos, idx, bc))
    # lowest claimant id per blocker wins
    winner = jnp.full(n + 1, n, jnp.int32).at[jnp.where(cand, b, n)].min(idx)
    sel = cand & (winner[bc] == idx)
    # blocker parked on the mover's own goal: swapping equal goals no-ops
    # (the reference deadlock) -> push the blocker toward the mover's cell.
    # The pushed pair now wants each other's cells, which Rule 4 would read
    # as a 2-cycle and rotate straight back to self-goals — undoing the push
    # and marking the delivery at the wrong cell — so pushed agents are
    # flagged and excluded from the cycle graph for the rest of the step;
    # the movement phase then resolves the pair as a mutual position swap
    # and the mover PHYSICALLY reaches the contested cell.
    same_goal = goal[bc] == goal
    sel3 = sel & ~same_goal
    push = sel & same_goal
    goal, slot = _apply_pair_swaps(goal, slot, sel3, bc, n)
    ge = jnp.concatenate([goal, jnp.zeros(1, goal.dtype)])
    ge = ge.at[jnp.where(push, bc, n)].set(jnp.where(push, pos, 0))
    goal = ge[:n]
    pe = jnp.concatenate([pushed, jnp.zeros(1, bool)])
    pushed = pe.at[jnp.where(push, bc, n)].set(True)[:n]

    # ---- Rule 4: rotate goals around blocking cycles ----
    at_goal = pos == goal
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    # blocking-graph successor; n = absorbing sentinel (chain breaks at
    # at-goal agents automatically: they have no move, f = n).  Chain edges
    # are always adjacent pairs, so pairwise visibility never restricts
    # them; the reference's decentralized mode instead requires the WHOLE
    # chain inside the *initiator's* radius (agent.rs:379-448, the
    # radius-15 nearby cache the initiator walks).  Matching that: a cycle
    # rotates iff at least one member sees every member within its own
    # radius (that member is the initiator broadcasting
    # target_rotation_request); all members then rotate consistently.
    # Freshly-pushed agents absorb (f = n): no cycle may pass through them
    # this step (see the push comment above).
    f = jnp.where(has_move & (b >= 0) & ~pushed, b, n)
    f_ext = jnp.concatenate([f, jnp.array([n], jnp.int32)])

    def cycle_scan(carry, _):
        y, on_cycle, within = carry
        y = f_ext[y]
        within = within & _within_radius(cfg, pos, idx, jnp.clip(y, 0, n - 1))
        return (y, on_cycle | ((y == idx) & within), within), None

    (_, init_ok, _), _ = jax.lax.scan(
        cycle_scan, (f, jnp.zeros(n, bool), jnp.ones(n, bool)), None,
        length=cfg.cycle_cap)
    if cfg.visibility_radius is None:
        on_cycle = init_ok  # global view: every member is its own initiator
    else:
        # plain cycle membership (no radius), then OR the initiator flag
        # around each cycle so members rotate all-or-nothing
        def plain_scan(carry, _):
            y, oc = carry
            y = f_ext[y]
            return (y, oc | (y == idx)), None

        (_, on_cycle_plain), _ = jax.lax.scan(
            plain_scan, (f, jnp.zeros(n, bool)), None, length=cfg.cycle_cap)
        init_ext = jnp.concatenate([init_ok, jnp.array([False])])

        def prop_scan(carry, _):
            y, any_ok = carry
            y = f_ext[y]
            return (y, any_ok | init_ext[y]), None

        (_, any_ok), _ = jax.lax.scan(
            prop_scan, (f, init_ok), None, length=cfg.cycle_cap)
        on_cycle = on_cycle_plain & any_ok
    # each cycle member hands its goal to its successor: perm q[f[x]] = x
    # (padded scratch slot n instead of mode="drop"; see _apply_pair_swaps)
    q = jnp.arange(n + 1, dtype=jnp.int32)
    q = q.at[jnp.where(on_cycle, f, n)].set(jnp.where(on_cycle, idx, n))
    q = q[:n]
    goal, slot = goal[q], slot[q]
    return goal, slot, pushed


def _movement_phase(cfg: SolverConfig, pos, goal, slot, nh_fn, occ, active):
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    bc = jnp.clip(b, 0, n - 1)

    # mutual position swap (ref :269-278): i and blocker want each other's cells
    mutual = has_move & (b >= 0) & (u[bc] == pos) & (b != idx)
    newpos = jnp.where(mutual, u, pos)
    decided = ~has_move | mutual

    def cond(state):
        _, _, changed, r = state
        return changed & (r < cfg.max_move_rounds)

    def body(state):
        decided, newpos, _, r = state
        # final occupancy of decided agents only (padded scratch cell at
        # index num_cells instead of mode="drop"; see _apply_pair_swaps)
        occf = jnp.full(cfg.num_cells + 1, -1, jnp.int32).at[
            jnp.where(decided & active, newpos, cfg.num_cells)].set(idx)
        # target available: nobody finalized there, and its original occupant
        # (if any) has finalized a move away
        orig = b  # original occupant of u (from occ at step start)
        orig_gone = (orig < 0) | (decided[bc] & (newpos[bc] != u))
        open_cell = (occf[u] == -1) & orig_gone
        claimant = ~decided & open_cell
        win = jnp.full(cfg.num_cells + 1, n, jnp.int32).at[
            jnp.where(claimant, u, cfg.num_cells)].min(idx)
        mover = claimant & (win[u] == idx)
        return (decided | mover, jnp.where(mover, u, newpos),
                jnp.any(mover), r + 1)

    decided, newpos, _, _ = jax.lax.while_loop(
        cond, body, (decided, newpos, jnp.bool_(True), jnp.int32(0)))
    return newpos


def step_parallel(cfg: SolverConfig, pos: jnp.ndarray, goal: jnp.ndarray,
                  slot: jnp.ndarray, dirs: jnp.ndarray,
                  active: jnp.ndarray | None = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One TSWAP timestep for all agents.

    Args:
      cfg: static solver config.
      pos:  (N,) int32 flat cell per agent (vertex-disjoint).
      goal: (N,) int32 flat goal cell per agent.
      slot: (N,) int32 agent -> direction-field row (a permutation).
      dirs: (N, ceil(H*W/8)) uint32 nibble-packed direction fields
        (ops.distance.pack_directions), row ``slot[i]`` is agent i's field
        (invariant: row slot[i] encodes descent toward goal[i]).

    Returns:
      (pos, goal, slot) after the step; ``dirs`` is never modified (goal
      exchange = slot permutation).
    """
    return step_with_next_hops(
        cfg, pos, goal, slot, lambda sl, po: next_hops(cfg, dirs, sl, po),
        active)


def step_with_next_hops(cfg: SolverConfig, pos, goal, slot, nh_fn,
                        active=None):
    """Step core parameterized by the next-hop lookup, so the sharded solver
    (parallel/sharded.py) can swap in a distributed field gather while rule
    semantics stay in exactly one place.

    ``active`` masks out padded/parked agent lanes entirely: inactive agents
    never occupy grid cells, never move, and never participate in swaps —
    the device-side mechanism behind fixed-capacity elastic populations
    (SURVEY §7 hard part 4: join/leave is host bookkeeping over a padded
    agent axis).
    """
    if active is None:
        active = jnp.ones(cfg.num_agents, bool)
    occ = _occupancy(cfg, pos, active)

    def round_body(_, gsp):
        goal, slot, pushed = gsp
        return _swap_phase_round(cfg, pos, goal, slot, pushed, nh_fn, occ,
                                 active)

    goal, slot, _ = jax.lax.fori_loop(
        0, cfg.swap_rounds, round_body,
        (goal, slot, jnp.zeros(cfg.num_agents, bool)))
    pos = _movement_phase(cfg, pos, goal, slot, nh_fn, occ, active)
    return pos, goal, slot
