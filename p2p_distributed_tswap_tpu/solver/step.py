"""Batched parallel TSWAP step kernel.

A parallel-consistent reformulation of the reference's sequential
``tswap_step`` (src/algorithm/tswap.rs:174-286), per SURVEY §7 hard part 1.
All agents act at once on dense (N,) tensors; conflicts resolve with
deterministic lowest-agent-id priority.  Per-agent A* is gone: each agent's
next hop is one gather from its goal's **direction field** (see
``ops.distance``), and goal exchanges never recompute fields — they permute
the ``slot`` indirection that maps agents to field rows.

Step anatomy (one call = one timestep for all N agents):

1. **Goal-swapping phase**, ``swap_rounds`` rounds of:
   - Rule 3 (ref :197-202): agents blocked by a neighbor parked on its own
     goal swap goals with it.  Multiple claimants on one blocker resolve to
     the lowest agent id; applied as a gather permutation of (goal, slot).
   - Rule 4 (ref :204-249): deadlock cycles in the blocking graph
     ``f(i) = occupant of i's next hop`` are detected exactly up to
     ``cycle_cap`` length by iterated composition, and every cycle rotates
     goals "backward along the chain" simultaneously: goal/slot of ``x`` move
     to ``f(x)`` — again a pure permutation.
2. **Movement phase** (ref :257-285): mutual swaps (adjacent pairs that want
   each other's cells) exchange positions; remaining agents cascade into
   free-or-vacated cells over fixpoint rounds, lowest id winning contested
   cells.  The cascade preserves vertex-disjointness and never lets two
   agents cross an edge except via a mutual swap.

Documented divergences from the sequential reference (validated empirically
for makespan parity in tests):
- swaps/rotations resolve per parallel round, not interleaved per agent;
- an agent moves at most once per step (the reference's in-pass mutual swap
  can move the partner again later in the same pass, tswap.rs:269-278);
- the movement cascade lets an agent enter a cell vacated this step by ANY
  mover, where the sequential pass only sees vacancies created by
  lower-indexed agents — strictly more progress per step;
- **push extension** (deliberate fix of a reference deadlock): when the
  blocker is parked on the mover's OWN goal (two tasks sharing a delivery
  cell — goals equal, so the reference's Rule-3 swap exchanges identical
  values and no-ops forever, tswap.rs:197-202), the blocker's goal is
  retargeted to the mover's current cell; the next movement phase resolves
  the pair as a mutual position swap.  Pushed goals are served by the
  goal-adjacency shortcut below, so the blocker's (stale) field row is
  never consulted for them.

Next-hop lookups enforce Rule 1 explicitly (at-goal agents never move, even
if their field row is stale) and apply a **goal-adjacency shortcut**: an
agent whose goal is exactly one cell away steps straight to it, bypassing
its direction field.  For field-backed goals both are no-ops (the field
would say the same); together they make pushed/stale-row (goal, slot) pairs
— which Rule-3/4 exchanges may hand around — exact within one extra step
for movers and inert for parked agents.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.ops.distance import (
    apply_direction,
    gather_packed,
)


def next_hops(cfg: SolverConfig, dirs: jnp.ndarray, slot: jnp.ndarray,
              pos: jnp.ndarray) -> jnp.ndarray:
    """Desired next cell per agent: one byte gather from that agent's
    nibble-packed direction field (row ``slot[i]``; see
    ``ops.distance.pack_directions``).  Equals ``pos`` for stay (at
    goal/unreachable)."""
    code = gather_packed(dirs, slot, pos)
    return apply_direction(pos, code, cfg.width)


def _occupancy(cfg: SolverConfig, pos: jnp.ndarray,
               active: jnp.ndarray) -> jnp.ndarray:
    """(HW+1,) int32: agent id at each cell, -1 if empty.  Inactive agents
    scatter to the padded scratch cell and never occupy the grid."""
    n = cfg.num_agents
    return jnp.full(cfg.num_cells + 1, -1, jnp.int32).at[
        jnp.where(active, pos, cfg.num_cells)].set(
        jnp.arange(n, dtype=jnp.int32))


def _blockers(occ, pos, u):
    """Agent occupying each agent's desired next cell (-1 free / no move)."""
    has_move = u != pos
    return jnp.where(has_move, occ[u], -1), has_move


def _within_radius(cfg: SolverConfig, pos, i_idx, j_idx):
    """Manhattan-visibility mask for agent pairs (decentralized mode,
    ref TSWAP_RADIUS=15 at src/bin/decentralized/agent.rs:796-801).
    Centralized mode (visibility_radius=None) sees everyone."""
    if cfg.visibility_radius is None:
        return jnp.ones_like(i_idx, bool)
    w = cfg.width
    a, b = pos[i_idx], pos[j_idx]
    mh = (jnp.abs(a % w - b % w) + jnp.abs(a // w - b // w))
    return mh <= cfg.visibility_radius


def _apply_pair_swaps(goal, slot, sel, partner, n):
    """Permute (goal, slot) by the disjoint transpositions {i <-> partner[i]}
    for selected i.

    Scatters go through a padded scratch slot at index ``n`` instead of
    relying on mode="drop": XLA's CPU backend has been observed to *wrap*
    out-of-bounds scatter rows for some shapes instead of dropping them.
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    p = jnp.arange(n + 1, dtype=jnp.int32)
    p = p.at[jnp.where(sel, idx, n)].set(jnp.where(sel, partner, n))
    p = p.at[jnp.where(sel, partner, n)].set(jnp.where(sel, idx, n))
    p = p[:n]
    return goal[p], slot[p]


def _hops(cfg: SolverConfig, nh_fn, slot, pos, goal, active):
    """Next hops with Rule 1 and the goal-adjacency shortcut explicit.

    Rule 1 (at-goal agents never move, ref tswap.rs:186) is enforced here
    directly instead of relying on the field saying STAY at the goal: a
    pushed agent's field row targets its OLD goal, and without the explicit
    check a parked pushed agent would wander off its goal following the
    stale row.  Together with the adjacency shortcut this bounds any
    stale-row effect to one extra step for moving agents and zero for
    parked ones."""
    u = jnp.where(active, nh_fn(slot, pos), pos)
    w = cfg.width
    mh = jnp.abs(pos % w - goal % w) + jnp.abs(pos // w - goal // w)
    u = jnp.where(active & (mh == 1), goal, u)
    return jnp.where(pos == goal, pos, u)


def _swap_phase_round(cfg: SolverConfig, pos, goal, slot, pushed, nh_fn, occ,
                      active):
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)

    # ---- Rule 3: swap goals with a blocker parked on its own goal ----
    at_goal = pos == goal
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    bc = jnp.clip(b, 0, n - 1)
    cand = (has_move & (b >= 0) & at_goal[bc]
            & _within_radius(cfg, pos, idx, bc))
    # lowest claimant id per blocker wins
    winner = jnp.full(n + 1, n, jnp.int32).at[jnp.where(cand, b, n)].min(idx)
    sel = cand & (winner[bc] == idx)
    # blocker parked on the mover's own goal: swapping equal goals no-ops
    # (the reference deadlock) -> push the blocker toward the mover's cell.
    # The pushed pair now wants each other's cells, which Rule 4 would read
    # as a 2-cycle and rotate straight back to self-goals — undoing the push
    # and marking the delivery at the wrong cell — so pushed agents are
    # flagged and excluded from the cycle graph for the rest of the step;
    # the movement phase then resolves the pair as a mutual position swap
    # and the mover PHYSICALLY reaches the contested cell.
    same_goal = goal[bc] == goal
    sel3 = sel & ~same_goal
    push = sel & same_goal
    goal, slot = _apply_pair_swaps(goal, slot, sel3, bc, n)
    ge = jnp.concatenate([goal, jnp.zeros(1, goal.dtype)])
    ge = ge.at[jnp.where(push, bc, n)].set(jnp.where(push, pos, 0))
    goal = ge[:n]
    pe = jnp.concatenate([pushed, jnp.zeros(1, bool)])
    pushed = pe.at[jnp.where(push, bc, n)].set(True)[:n]

    # ---- Rule 4: rotate goals around blocking cycles ----
    at_goal = pos == goal
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    # blocking-graph successor; n = absorbing sentinel (chain breaks at
    # at-goal agents automatically: they have no move, f = n).  Chain edges
    # are always adjacent pairs, so pairwise visibility never restricts
    # them; the reference's decentralized mode instead requires the WHOLE
    # chain inside the *initiator's* radius (agent.rs:379-448, the
    # radius-15 nearby cache the initiator walks).  Matching that: a cycle
    # rotates iff at least one member sees every member within its own
    # radius (that member is the initiator broadcasting
    # target_rotation_request); all members then rotate consistently.
    # Freshly-pushed agents absorb (f = n): no cycle may pass through them
    # this step (see the push comment above).
    f = jnp.where(has_move & (b >= 0) & ~pushed, b, n)
    f_ext = jnp.concatenate([f, jnp.array([n], jnp.int32)])

    if cfg.visibility_radius is None:
        def cycle_scan(carry, _):
            y, on_cycle = carry
            y = f_ext[y]
            return (y, on_cycle | (y == idx)), None

        (_, on_cycle), _ = jax.lax.scan(
            cycle_scan, (f, jnp.zeros(n, bool)), None,
            length=cfg.cycle_cap)  # global view: everyone is an initiator
    else:
        # One fused walk computes BOTH plain cycle membership and the
        # radius-constrained initiator flag (they share the same y
        # trajectory — round 3 ran them as two separate scan chains, half
        # of the decent-mode scan premium, VERDICT r3 weak #5); a second
        # walk then ORs the initiator flag around each cycle so members
        # rotate all-or-nothing.
        def member_scan(carry, _):
            y, oc, ok, within = carry
            y = f_ext[y]
            within = within & _within_radius(cfg, pos, idx,
                                             jnp.clip(y, 0, n - 1))
            hit = y == idx
            return (y, oc | hit, ok | (hit & within), within), None

        (_, on_cycle_plain, init_ok, _), _ = jax.lax.scan(
            member_scan,
            (f, jnp.zeros(n, bool), jnp.zeros(n, bool), jnp.ones(n, bool)),
            None, length=cfg.cycle_cap)
        init_ext = jnp.concatenate([init_ok, jnp.array([False])])

        def prop_scan(carry, _):
            y, any_ok = carry
            y = f_ext[y]
            return (y, any_ok | init_ext[y]), None

        (_, any_ok), _ = jax.lax.scan(
            prop_scan, (f, init_ok), None, length=cfg.cycle_cap)
        on_cycle = on_cycle_plain & any_ok
    # each cycle member hands its goal to its successor: perm q[f[x]] = x
    # (padded scratch slot n instead of mode="drop"; see _apply_pair_swaps)
    q = jnp.arange(n + 1, dtype=jnp.int32)
    q = q.at[jnp.where(on_cycle, f, n)].set(jnp.where(on_cycle, idx, n))
    q = q[:n]
    goal, slot = goal[q], slot[q]
    return goal, slot, pushed


def _movement_phase(cfg: SolverConfig, pos, goal, slot, nh_fn, occ, active):
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    b, has_move = _blockers(occ, pos, u)
    bc = jnp.clip(b, 0, n - 1)

    # mutual position swap (ref :269-278): i and blocker want each other's cells
    mutual = has_move & (b >= 0) & (u[bc] == pos) & (b != idx)
    newpos = jnp.where(mutual, u, pos)
    decided = ~has_move | mutual

    def cond(state):
        _, _, changed, r = state
        return changed & (r < cfg.max_move_rounds)

    def body(state):
        decided, newpos, _, r = state
        # final occupancy of decided agents only (padded scratch cell at
        # index num_cells instead of mode="drop"; see _apply_pair_swaps)
        occf = jnp.full(cfg.num_cells + 1, -1, jnp.int32).at[
            jnp.where(decided & active, newpos, cfg.num_cells)].set(idx)
        # target available: nobody finalized there, and its original occupant
        # (if any) has finalized a move away
        orig = b  # original occupant of u (from occ at step start)
        orig_gone = (orig < 0) | (decided[bc] & (newpos[bc] != u))
        open_cell = (occf[u] == -1) & orig_gone
        claimant = ~decided & open_cell
        win = jnp.full(cfg.num_cells + 1, n, jnp.int32).at[
            jnp.where(claimant, u, cfg.num_cells)].min(idx)
        mover = claimant & (win[u] == idx)
        return (decided | mover, jnp.where(mover, u, newpos),
                jnp.any(mover), r + 1)

    decided, newpos, _, _ = jax.lax.while_loop(
        cond, body, (decided, newpos, jnp.bool_(True), jnp.int32(0)))
    return newpos


def step_parallel(cfg: SolverConfig, pos: jnp.ndarray, goal: jnp.ndarray,
                  slot: jnp.ndarray, dirs: jnp.ndarray,
                  active: jnp.ndarray | None = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One TSWAP timestep for all agents.

    Args:
      cfg: static solver config.
      pos:  (N,) int32 flat cell per agent (vertex-disjoint).
      goal: (N,) int32 flat goal cell per agent.
      slot: (N,) int32 agent -> direction-field row (a permutation).
      dirs: (N, ceil(H*W/8)) uint32 nibble-packed direction fields
        (ops.distance.pack_directions), row ``slot[i]`` is agent i's field
        (invariant: row slot[i] encodes descent toward goal[i]).

    Returns:
      (pos, goal, slot) after the step; ``dirs`` is never modified (goal
      exchange = slot permutation).
    """
    return step_with_next_hops(
        cfg, pos, goal, slot, lambda sl, po: next_hops(cfg, dirs, sl, po),
        active)


def _within_radius_pts(cfg: SolverConfig, a, b):
    """Manhattan-visibility between explicit cell arrays — the stale-mode
    variant of :func:`_within_radius` where the observed side comes from the
    broadcast view, not the true positions."""
    if cfg.visibility_radius is None:
        return jnp.ones_like(a, bool)
    w = cfg.width
    mh = jnp.abs(a % w - b % w) + jnp.abs(a // w - b // w)
    return mh <= cfg.visibility_radius


def _view_occupancy(cfg: SolverConfig, vpos, visible):
    """(HW+1,) int32 agent id believed to occupy each cell, -1 if believed
    empty.  Unlike true occupancy, stale positions CAN coincide (two
    last-broadcast entries on one cell); the lowest id wins
    deterministically."""
    n = cfg.num_agents
    occ = jnp.full(cfg.num_cells + 1, n, jnp.int32).at[
        jnp.where(visible, vpos, cfg.num_cells)].min(
        jnp.arange(n, dtype=jnp.int32))
    return jnp.where(occ == n, -1, occ)


def step_stale(cfg: SolverConfig, pos, goal, slot, nh_fn, vpos, vgoal,
               visible, active):
    """One decentralized TSWAP timestep under STALE views — the device
    analog of the reference's actual decentralized tick
    (src/bin/decentralized/agent.rs:730-927): each agent takes ONE action
    (Move / WaitForGoalSwap / WaitForRotation / Wait) from its own fresh
    state plus the last-broadcast ``(vpos, vgoal)`` view of its neighbors,
    and goal exchanges do NOT commit here — they are returned as a pending
    permutation (+ push targets) the caller commits ``swap_commit_delay``
    steps later, mirroring the non-atomic wire coordination
    (agent.rs:1041-1107: the peer mutates its goal at request-receipt time,
    the requester at response-receipt time).

    Decisions-vs-physics split (documented divergence from the reference,
    where positions are self-declared and agents can transiently overlap):
    DECISIONS read the stale view, but movement arbitration stays physical
    — the cascade grants a move only into a cell that is actually free or
    vacated, so recorded paths remain vertex-disjoint and the bench
    invariants stay certifiable.  An agent whose believed-free cell is
    actually occupied simply stays (where the reference agent would have
    overlapped); an agent whose believed-occupied cell is actually free
    waits a round it didn't need to.

    Rule-4 chains are walked over a stale blocking graph, like the
    reference initiator walking its nearby cache (agent.rs:379-448): the
    successor of agent j is whoever the VIEW says occupies j's desired
    next cell.  One shared successor function keeps every detected ring
    consistent (the reference gets per-ring consistency because the
    initiator's message defines the participant list, agent.rs:909-917);
    the staleness enters through the view occupancy — rings can thread
    through ghosts of agents that have since moved, rotating goals that
    did not need rotating, exactly the reference pathology.

    Mutual position swaps are disabled: with stale views two agents cannot
    coordinate a simultaneous edge exchange (the reference's decentralized
    mode has no mutual-swap action either — face-offs resolve as 2-cycle
    rotations, agent.rs:907-921).

    Returns ``(newpos, pend_from, pend_push)``: ``pend_from`` is the
    goal-source permutation to commit later (identity where no exchange),
    ``pend_push`` the pushed-goal cell per agent (-1 none).
    """
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)
    occ = _occupancy(cfg, pos, active)          # physical truth
    vis = visible & active
    vocc = _view_occupancy(cfg, vpos, vis)

    # own desired next hop: fresh self-knowledge (pos, goal, own field row)
    u = _hops(cfg, nh_fn, slot, pos, goal, active)
    has_move = active & (u != pos)
    bv = jnp.where(has_move, vocc[u], -1)
    bv = jnp.where(bv == idx, -1, bv)           # own stale ghost != blocker
    bvc = jnp.clip(bv, 0, n - 1)
    # an out-of-radius occupant was evicted from the cache (ref
    # agent.rs:797): the cell is believed free
    bv = jnp.where((bv >= 0) & _within_radius_pts(cfg, pos, vpos[bvc]),
                   bv, -1)
    bvc = jnp.clip(bv, 0, n - 1)
    blocked = bv >= 0

    # ---- Rule 3 decision on the view: blocker parked (in view) on its
    # (view) goal -> WaitForGoalSwap ----
    parked_v = vpos == vgoal
    cand3 = blocked & parked_v[bvc]
    same_goal = vgoal[bvc] == goal              # push case (shared delivery)
    # pending exchanges must form a permutation, so each agent joins at
    # most ONE pair: grant each blocker its lowest claimant, then resolve
    # claimant-vs-blocker role conflicts by lowest claimant id
    grant = jnp.full(n + 1, n, jnp.int32).at[
        jnp.where(cand3, bvc, n)].min(idx)
    win = cand3 & (grant[bvc] == idx)
    tgt = grant[:n]                             # claimant granted agent j
    keep = win & ((tgt[idx] == n) | (idx < tgt[idx]))
    keep = keep & ~(win[bvc] & (bvc < idx))
    push = keep & same_goal
    sw = keep & ~same_goal

    pend_from = jnp.arange(n + 1, dtype=jnp.int32)
    pend_from = pend_from.at[jnp.where(sw, idx, n)].set(
        jnp.where(sw, bvc, n))
    pend_from = pend_from.at[jnp.where(sw, bvc, n)].set(
        jnp.where(sw, idx, n))
    pend_push = jnp.full(n + 1, -1, jnp.int32).at[
        jnp.where(push, bvc, n)].set(jnp.where(push, pos, -1))[:n]

    # ---- Rule 4 decision on the view graph: deadlock cycles over ONE
    # shared successor function so detected cycles are consistent rings
    # (the reference's rotation is consistent per ring for the same
    # reason: the initiator's message defines the participant list,
    # agent.rs:909-917).  f(j) = the agent j believes occupies j's desired
    # next cell: fresh own move, stale blocker lookup — exactly what j's
    # own decision tick computes.  Pair participants are excluded (their
    # action this step is the swap). ----
    in_pair = jnp.zeros(n + 1, bool).at[
        jnp.where(keep, idx, n)].set(True).at[
        jnp.where(keep, bvc, n)].set(True)[:n]
    # goal-mutual pairs (each holds the other's cell as GOAL — the state a
    # committed push leaves behind) resolve PHYSICALLY as a terminal
    # mutual position swap in the movement phase below; they must not ALSO
    # read as a Rule-4 2-cycle, or the pended rotation undoes the swap one
    # step later and the pair oscillates forever (positions swap, then
    # goals swap back, ad infinitum).
    occ_u = jnp.where(has_move, occ[u], -1)
    ouc = jnp.clip(occ_u, 0, n - 1)
    mutual = (has_move & (occ_u >= 0) & (occ_u != idx)
              & (goal == u) & (goal[ouc] == pos) & (u[ouc] == pos))
    fmask = blocked & ~in_pair & ~in_pair[bvc] & ~mutual & ~mutual[bvc]
    f = jnp.where(fmask, bv, n)
    f_ext = jnp.concatenate([f, jnp.array([n], jnp.int32)])

    # one fused walk for plain membership + radius-checked initiator flag
    # (same trajectory; see _swap_phase_round's member_scan)
    def member_scan(carry, _):
        y, oc, ok, within = carry
        y = f_ext[y]
        within = within & _within_radius_pts(
            cfg, pos, vpos[jnp.clip(y, 0, n - 1)]) & (y < n)
        hit = y == idx
        return (y, oc | hit, ok | (hit & within), within), None

    (_, on_cycle_plain, init_ok, _), _ = jax.lax.scan(
        member_scan,
        (f, jnp.zeros(n, bool), jnp.zeros(n, bool), jnp.ones(n, bool)),
        None, length=cfg.cycle_cap)
    # all-or-nothing per cycle: members rotate iff SOME member's own walk
    # succeeded (that member is the initiator broadcasting the rotation)
    init_ext = jnp.concatenate([init_ok, jnp.array([False])])

    def prop_scan(carry, _):
        y, any_ok = carry
        y = f_ext[y]
        return (y, any_ok | init_ext[y]), None

    (_, any_ok), _ = jax.lax.scan(
        prop_scan, (f, init_ok), None, length=cfg.cycle_cap)
    on_cycle = on_cycle_plain & any_ok
    # members hand goals backward along the ring, committing with the
    # same latency as swaps (the rotation message arrives next tick)
    pend_from = pend_from.at[jnp.where(on_cycle, f, n)].set(
        jnp.where(on_cycle, idx, n))
    pend_from = pend_from[:n]

    # ---- movement: Move decisions execute against physical occupancy ----
    # Only believed-free moves are attempted (ref Rule 2); every blocked
    # agent's action is some flavor of wait (WaitForGoalSwap /
    # WaitForRotation / Wait), so the mover set is simply the unblocked.
    # (_movement_cascade additionally executes the terminal mutual swap of
    # committed push pairs — the `mutual` mask computed above.)
    movers = has_move & ~blocked
    newpos = _movement_cascade(cfg, pos, u, movers, occ, active, mutual)
    return newpos, pend_from, pend_push


def _movement_cascade(cfg: SolverConfig, pos, u, want, occ, active, mutual):
    """Physical movement arbitration for stale mode: like
    :func:`_movement_phase` but with an explicit mover mask and, in
    general, NO mutual swaps (see :func:`step_stale`).

    The single exception is the **terminal mutual swap of a goal-mutual
    pair**: two adjacent agents whose goals are each other's cells (the
    state a committed push leaves behind — and the state the atomic path
    resolves with its in-step mutual position swap).  Without it the pair
    would either deadlock (each waiting for the other to vacate) or — if
    Rule 4 reads the face-off as a 2-cycle — rotate the push away and mark
    the delivery at the WRONG cell.  The swap is sanctioned coordination:
    the push's request/response handshake is exactly the wire exchange
    that establishes it (same reasoning as the atomic path's push,
    step.py Rule-3 comment).  ``mutual`` is computed by the caller
    (step_stale), which also excludes these pairs from the Rule-4 cycle
    graph — the same face-off must not both swap positions AND pend a
    rotation, or the two resolutions undo each other forever."""
    n = cfg.num_agents
    idx = jnp.arange(n, dtype=jnp.int32)
    b = jnp.where(want & ~mutual, occ[u], -1)   # true occupant of target
    bc = jnp.clip(b, 0, n - 1)
    decided = mutual | ~want
    newpos = jnp.where(mutual, u, pos)

    def cond(state):
        _, _, changed, r = state
        return changed & (r < cfg.max_move_rounds)

    def body(state):
        decided, newpos, _, r = state
        occf = jnp.full(cfg.num_cells + 1, -1, jnp.int32).at[
            jnp.where(decided & active, newpos, cfg.num_cells)].set(idx)
        orig_gone = (b < 0) | (decided[bc] & (newpos[bc] != u))
        open_cell = (occf[u] == -1) & orig_gone
        claimant = ~decided & open_cell
        winm = jnp.full(cfg.num_cells + 1, n, jnp.int32).at[
            jnp.where(claimant, u, cfg.num_cells)].min(idx)
        mover = claimant & (winm[u] == idx)
        return (decided | mover, jnp.where(mover, u, newpos),
                jnp.any(mover), r + 1)

    decided, newpos, _, _ = jax.lax.while_loop(
        cond, body, (decided, newpos, jnp.bool_(True), jnp.int32(0)))
    return newpos


def step_with_next_hops(cfg: SolverConfig, pos, goal, slot, nh_fn,
                        active=None):
    """Step core parameterized by the next-hop lookup, so the sharded solver
    (parallel/sharded.py) can swap in a distributed field gather while rule
    semantics stay in exactly one place.

    ``active`` masks out padded/parked agent lanes entirely: inactive agents
    never occupy grid cells, never move, and never participate in swaps —
    the device-side mechanism behind fixed-capacity elastic populations
    (SURVEY §7 hard part 4: join/leave is host bookkeeping over a padded
    agent axis).
    """
    if active is None:
        active = jnp.ones(cfg.num_agents, bool)
    occ = _occupancy(cfg, pos, active)

    def round_body(_, gsp):
        goal, slot, pushed = gsp
        return _swap_phase_round(cfg, pos, goal, slot, pushed, nh_fn, occ,
                                 active)

    goal, slot, _ = jax.lax.fori_loop(
        0, cfg.swap_rounds, round_body,
        (goal, slot, jnp.zeros(cfg.num_agents, bool)))
    pos = _movement_phase(cfg, pos, goal, slot, nh_fn, occ, active)
    return pos, goal, slot
