"""Offline MAPD loop — the TPU equivalent of the reference's ``tswap_mapd``
(src/algorithm/tswap.rs:39-172): greedy nearest-pickup task assignment, the
Idle -> ToPickup -> ToDelivery machine, TSWAP stepping, per-step path
recording, and the all-done-or-horizon termination rule — as one jitted
``lax.while_loop`` over device state.

The one genuinely new mechanism versus the reference is **replanning**: goal
changes from the task lifecycle (assignment, pickup -> delivery) need fresh
direction fields.  Goal *swaps* never do (slot permutation), so the per-step
replan set is small; it is processed in static-size chunks of
``cfg.replan_chunk`` fields per round (fast-sweeping over a (R, H, W) batch),
looping until the set drains.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from p2p_distributed_tswap_tpu.core.agent import AgentPhase, AgentState
from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.obs import trace
from p2p_distributed_tswap_tpu.ops.distance import (
    PACKED_STAY,
    direction_fields,
    pack_directions,
    packed_cells,
)
from p2p_distributed_tswap_tpu.solver import step as step_mod
from p2p_distributed_tswap_tpu.solver.step import (
    step_parallel,
    step_with_next_hops,
)

_FAR = jnp.int32(1 << 20)  # > any grid manhattan distance


@struct.dataclass
class MapdState:
    pos: jnp.ndarray          # (N,) int32 flat cell
    goal: jnp.ndarray         # (N,) int32 flat cell
    slot: jnp.ndarray         # (N,) int32 agent -> field row
    dirs: jnp.ndarray         # (N, ceil(HW/8)) uint32 packed direction fields
    phase: jnp.ndarray        # (N,) int8 AgentPhase
    agent_task: jnp.ndarray   # (N,) int32 task index or -1
    task_used: jnp.ndarray    # (T,) bool
    need_replan: jnp.ndarray  # (N,) bool: agent's goal changed, field stale
    t: jnp.ndarray            # () int32 timestep counter
    paths_pos: jnp.ndarray    # (Tmax+1, N) int32 recorded positions
    paths_state: jnp.ndarray  # (Tmax+1, N) int8 recorded AgentState
    # --- stale/async decentralized view (cfg.stale_mode; inert otherwise,
    # see solver/step.py step_stale) ---
    vpos: jnp.ndarray         # (N,) int32 last-broadcast position
    vgoal: jnp.ndarray        # (N,) int32 last-broadcast goal
    vstamp: jnp.ndarray       # (N,) int32 step of last broadcast
    pend_from: jnp.ndarray    # (N,) int32 pending goal-source permutation
    pend_push: jnp.ndarray    # (N,) int32 pending pushed-goal cell or -1


def init_state(cfg: SolverConfig, starts: jnp.ndarray,
               num_tasks: int) -> MapdState:
    n, hw, tmax = cfg.num_agents, cfg.num_cells, cfg.max_timesteps
    # path buffers shrink to one dummy row when recording is off
    tdim = tmax + 1 if cfg.record_paths else 1
    return MapdState(
        pos=jnp.asarray(starts, jnp.int32),
        goal=jnp.asarray(starts, jnp.int32),
        slot=jnp.arange(n, dtype=jnp.int32),
        dirs=jnp.full((n, packed_cells(hw)), PACKED_STAY, jnp.uint32),
        phase=jnp.full(n, AgentPhase.IDLE, jnp.int8),
        agent_task=jnp.full(n, -1, jnp.int32),
        task_used=jnp.zeros(num_tasks, bool),
        # All rows start stale: an uncomputed all-STAY row is only valid while
        # its agent sits on its start cell, but Rule-3 swaps can hand the row
        # to an agent elsewhere — so every field is computed on the first step.
        need_replan=jnp.ones(n, bool),
        t=jnp.int32(0),
        paths_pos=jnp.zeros((tdim, n), jnp.int32),
        paths_state=jnp.zeros((tdim, n), jnp.int8),
        # everyone "broadcast" at t=0 from their start cell (the reference's
        # occupied/initial-position protocol seeds every cache)
        vpos=jnp.asarray(starts, jnp.int32),
        vgoal=jnp.asarray(starts, jnp.int32),
        vstamp=jnp.zeros(n, jnp.int32),
        pend_from=jnp.arange(n, dtype=jnp.int32),
        pend_push=jnp.full(n, -1, jnp.int32),
    )


def _transitions(cfg: SolverConfig, s: MapdState, tasks: jnp.ndarray) -> MapdState:
    """Arrival transitions (ref tswap.rs:106-121), vectorized: transitions of
    distinct agents are independent, so order does not matter."""
    arrived = s.pos == s.goal
    tp = arrived & (s.phase == AgentPhase.TO_PICKUP)
    td = arrived & (s.phase == AgentPhase.TO_DELIVERY)
    task = jnp.clip(s.agent_task, 0)
    goal = jnp.where(tp, tasks[task, 1], s.goal)
    phase = jnp.where(tp, AgentPhase.TO_DELIVERY,
                      jnp.where(td, AgentPhase.IDLE, s.phase)).astype(jnp.int8)
    agent_task = jnp.where(td, -1, s.agent_task)
    return s.replace(goal=goal, phase=phase, agent_task=agent_task,
                     need_replan=s.need_replan | tp)


def _nearest_unused(cfg: SolverConfig, pos: jnp.ndarray,
                    task_used: jnp.ndarray, tasks: jnp.ndarray):
    """Per-agent (distance, index) of the nearest unused task pickup,
    Manhattan metric, lowest task index on ties (the reference's
    ``min_by_key`` keeps the first minimum).  Chunked over the task axis so
    transient memory is (N, assign_chunk) int32, never the full (N, T)
    matrix (400 MB at the FLAGSHIP rung, 40 GB at EXTREME)."""
    n, w = cfg.num_agents, cfg.width
    t = tasks.shape[0]
    c = min(cfg.assign_chunk, t)
    nchunks = -(-t // c)
    pad = nchunks * c - t
    px = jnp.pad(tasks[:, 0] % w, (0, pad))
    py = jnp.pad(tasks[:, 0] // w, (0, pad))
    used = jnp.pad(task_used, (0, pad), constant_values=True)
    ax, ay = pos % w, pos // w

    def chunk(carry, ci):
        best_d, best_k = carry
        o = ci * c
        cpx = jax.lax.dynamic_slice_in_dim(px, o, c)
        cpy = jax.lax.dynamic_slice_in_dim(py, o, c)
        cused = jax.lax.dynamic_slice_in_dim(used, o, c)
        d = (jnp.abs(cpx[None, :] - ax[:, None])
             + jnp.abs(cpy[None, :] - ay[:, None]))
        d = jnp.where(cused[None, :], _FAR, d)
        k = jnp.argmin(d, axis=1).astype(jnp.int32)  # first min in chunk
        dk = jnp.take_along_axis(d, k[:, None], axis=1)[:, 0]
        better = dk < best_d  # strict: ties keep the earlier chunk's index
        return (jnp.where(better, dk, best_d),
                jnp.where(better, o + k, best_k)), None

    init = (jnp.full(n, _FAR, jnp.int32), jnp.zeros(n, jnp.int32))
    (bd, bk), _ = jax.lax.scan(chunk, init,
                               jnp.arange(nchunks, dtype=jnp.int32))
    return bd, bk


def _assign(cfg: SolverConfig, s: MapdState, tasks: jnp.ndarray) -> MapdState:
    """Greedy nearest-pickup assignment (ref tswap.rs:123-138), parallelized.

    The reference assigns in agent-id order — a serial chain of N argmins
    over T tasks, O(N*T) sequential work (the round-1 scaling wall).  Here
    every idle agent proposes its nearest unused task at once; contested
    tasks go to the lowest proposing agent id; losers re-propose next round
    over the shrunken pool, until no proposal succeeds.  Each round claims
    >=1 task, so rounds <= min(#idle, #unused) — in practice a handful.

    Documented approximation (validated for makespan parity like the other
    parallel-ordering divergences, tests/test_solver.py): the result can
    differ from the sequential greedy when agent j (j > i) wins task B in an
    early round while agent i — having lost its first choice A — would have
    claimed B before j in the sequential id-order scan.  The oracle
    (solver/oracle.py) keeps the exact sequential semantics."""
    n = cfg.num_agents
    t = tasks.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)

    def cond(carry):
        return carry[-1]

    def body(carry):
        task_used, goal, phase, agent_task, need, _ = carry
        idle = phase == AgentPhase.IDLE
        bd, bk = _nearest_unused(cfg, s.pos, task_used, tasks)
        want = idle & (bd < _FAR)
        # lowest claimant id per task wins (scratch slot t: no OOB scatter)
        winner = jnp.full(t + 1, n, jnp.int32).at[
            jnp.where(want, bk, t)].min(idx)
        win = want & (winner[bk] == idx)
        claimed = jnp.zeros(t + 1, bool).at[jnp.where(win, bk, t)].set(True)
        return (task_used | claimed[:t],
                jnp.where(win, tasks[bk, 0], goal),
                jnp.where(win, AgentPhase.TO_PICKUP, phase).astype(jnp.int8),
                jnp.where(win, bk, agent_task),
                need | win,
                jnp.any(win))

    init = (s.task_used, s.goal, s.phase, s.agent_task, s.need_replan,
            jnp.bool_(True))
    task_used, goal, phase, agent_task, need, _ = jax.lax.while_loop(
        cond, body, init)
    return s.replace(task_used=task_used, goal=goal, phase=phase,
                     agent_task=agent_task, need_replan=need)


def _replan(cfg: SolverConfig, s: MapdState, free: jnp.ndarray) -> MapdState:
    """Recompute direction-field rows for agents whose goal changed, in
    static-size chunks per round until the set drains.

    Chunking strategy: sweep cost is O(chunk * H * W) per round regardless
    of how few rows are actually dirty, and at steady state only a handful
    of arrivals per step need fields — so the in-step loop uses the NARROW
    ``replan_chunk_small``.  The t=0 burst (all N fields at once) is
    handled by :func:`prime_fields` with the wide ``replan_chunk`` instead.
    Deliberately a single while_loop with one chunk width: a per-round
    ``lax.cond`` between two widths executed at wide-branch cost on the
    axon backend once fused into the full step program (~1.45 s/step), and
    a wide-then-narrow pair of while_loops was slower still (~2.7 s/step)
    even with the wide loop at zero iterations — vs 0.19 s/step for this
    shape at the 1k-512 rung."""
    n = cfg.num_agents
    r = min(cfg.replan_chunk_small, n)
    idx = jnp.arange(n, dtype=jnp.int32)

    def cond(carry):
        dirs, need = carry
        return jnp.any(need)

    def body(carry):
        dirs, need = carry
        priority = jnp.where(need, idx, n)
        sel = -jax.lax.top_k(-priority, r)[0]       # r lowest flagged ids
        valid = sel < n
        selc = jnp.clip(sel, 0, n - 1)
        fields = direction_fields(free, s.goal[selc],
                                  max_rounds=cfg.max_sweep_rounds)
        fields = pack_directions(fields.reshape(r, cfg.num_cells))
        # Invalid lanes clip to agent n-1, whose (goal, slot) pair is still
        # consistent — so their writes are redundant but *correct*, and no
        # out-of-bounds scatter index is ever needed (XLA CPU has been seen
        # wrapping OOB scatter rows instead of dropping them).
        dirs = dirs.at[s.slot[selc]].set(fields)
        cleared = jnp.zeros(n, bool).at[selc].max(valid)
        return dirs, need & ~cleared

    dirs, need = jax.lax.while_loop(cond, body, (s.dirs, s.need_replan))
    return s.replace(dirs=dirs, need_replan=need)


def prime_fields(cfg: SolverConfig, s: MapdState, free: jnp.ndarray) -> MapdState:
    """Compute direction fields for EVERY agent's current goal in wide
    static chunks — the t=0 burst, hoisted out of the per-step replan loop.

    One ``lax.scan`` of ceil(N / replan_chunk) steps (static trip count, no
    data-dependent control flow), each sweeping a (replan_chunk, H, W)
    batch.  Call once after initial task assignment (``prepare_state``);
    afterwards the per-step narrow replan only ever sees incremental goal
    changes.  The tail chunk clips to agent n-1 and recomputes a few rows
    redundantly — their (goal, slot) pairs are consistent, so the extra
    writes are correct."""
    n, r = cfg.num_agents, min(cfg.replan_chunk, cfg.num_agents)
    nchunks = -(-n // r)
    lane = jnp.arange(r, dtype=jnp.int32)

    def chunk(dirs, ci):
        sel = jnp.clip(ci * r + lane, 0, n - 1)
        fields = direction_fields(free, s.goal[sel],
                                  max_rounds=cfg.max_sweep_rounds)
        dirs = dirs.at[s.slot[sel]].set(
            pack_directions(fields.reshape(r, cfg.num_cells)))
        return dirs, None

    dirs, _ = jax.lax.scan(chunk, s.dirs,
                           jnp.arange(nchunks, dtype=jnp.int32))
    return s.replace(dirs=dirs,
                     need_replan=jnp.zeros(n, bool))


def _record(cfg: SolverConfig, s: MapdState) -> MapdState:
    """Path recording (ref tswap.rs:143-158); compile-time no-op (beyond the
    timestep increment) when ``cfg.record_paths`` is off."""
    if not cfg.record_paths:
        return s.replace(t=s.t + 1)
    state = jnp.where(
        s.phase == AgentPhase.IDLE, AgentState.IDLE,
        jnp.where(s.phase == AgentPhase.TO_PICKUP, AgentState.PICKING,
                  jnp.where(s.pos == s.goal, AgentState.DELIVERED,
                            AgentState.CARRYING))).astype(jnp.int8)
    return s.replace(
        paths_pos=jax.lax.dynamic_update_index_in_dim(
            s.paths_pos, s.pos, s.t, axis=0),
        paths_state=jax.lax.dynamic_update_index_in_dim(
            s.paths_state, state, s.t, axis=0),
        t=s.t + 1)


def _commit_pending(cfg: SolverConfig, s: MapdState) -> MapdState:
    """Apply the delayed goal exchanges decided ``swap_commit_delay`` steps
    ago (solver/step.py step_stale): permute (goal, slot, need_replan) by
    ``pend_from`` — exchanged rows stay consistent with exchanged goals —
    then land pushed goals, whose rows ARE stale and flagged for replan.
    Identity pend is a no-op, so calling unconditionally is safe."""
    p = s.pend_from
    goal, slot, need = s.goal[p], s.slot[p], s.need_replan[p]
    pushed = s.pend_push >= 0
    goal = jnp.where(pushed, s.pend_push, goal)
    n = cfg.num_agents
    return s.replace(goal=goal, slot=slot, need_replan=need | pushed,
                     pend_from=jnp.arange(n, dtype=jnp.int32),
                     pend_push=jnp.full(n, -1, jnp.int32))


def _broadcast_view(cfg: SolverConfig, s: MapdState) -> MapdState:
    """Refresh the shared neighbor view for agents whose broadcast is due
    this step — every ``view_refresh_steps`` steps on a per-agent phase
    offset, the decoupled-cadence analog of the reference's per-process
    500 ms position timers (agent.rs:730-789)."""
    n, k = cfg.num_agents, cfg.view_refresh_steps
    phase = jnp.arange(n, dtype=jnp.int32) % k
    due = (s.t + phase) % k == 0
    return s.replace(vpos=jnp.where(due, s.pos, s.vpos),
                     vgoal=jnp.where(due, s.goal, s.vgoal),
                     vstamp=jnp.where(due, s.t, s.vstamp))


def mapd_step(cfg: SolverConfig, s: MapdState, tasks: jnp.ndarray,
              free: jnp.ndarray, replan_fn=None, nh_factory=None) -> MapdState:
    """One full MAPD timestep: (pending-commit) -> transitions ->
    assignment -> replan -> TSWAP step -> record.

    ``replan_fn(cfg, s, free)`` and ``nh_factory(cfg, dirs) -> nh_fn`` let the
    sharded solver (parallel/sharded.py) substitute its distributed field
    machinery while the MAPD sequencing lives in exactly one place.

    Stale mode (cfg.stale_mode): last step's pending goal exchanges commit
    FIRST (they were "on the wire" during the previous step), then the
    normal task lifecycle runs, then the stale-view decision/movement step
    replaces the fresh-atomic kernel.  With ``swap_commit_delay == 0`` the
    exchange instead commits at the END of the same step (decisions were
    still taken on the stale view, but no in-flight window exists).
    """
    stale = cfg.stale_mode
    if stale:
        s = _commit_pending(cfg, s)
    s = _transitions(cfg, s, tasks)
    any_idle = jnp.any((s.phase == AgentPhase.IDLE) & ~jnp.all(s.task_used))
    s = jax.lax.cond(any_idle, lambda s: _assign(cfg, s, tasks), lambda s: s, s)
    s = (replan_fn or _replan)(cfg, s, free)
    if stale:
        s = _broadcast_view(cfg, s)
        if nh_factory is None:
            nh_fn = lambda sl, po: step_mod.next_hops(cfg, s.dirs, sl, po)
        else:
            nh_fn = nh_factory(cfg, s.dirs)
        visible = (jnp.ones(cfg.num_agents, bool)
                   if cfg.view_ttl_steps is None
                   else (s.t - s.vstamp) <= cfg.view_ttl_steps)
        pos, pend_from, pend_push = step_mod.step_stale(
            cfg, s.pos, s.goal, s.slot, nh_fn, s.vpos, s.vgoal, visible,
            jnp.ones(cfg.num_agents, bool))
        s = s.replace(pos=pos, pend_from=pend_from, pend_push=pend_push)
        if cfg.swap_commit_delay == 0:
            s = _commit_pending(cfg, s)
        return _record(cfg, s)
    if nh_factory is None:
        pos, goal, slot = step_parallel(cfg, s.pos, s.goal, s.slot, s.dirs)
    else:
        pos, goal, slot = step_with_next_hops(
            cfg, s.pos, s.goal, s.slot, nh_factory(cfg, s.dirs))
    return _record(cfg, s.replace(pos=pos, goal=goal, slot=slot))


def _finished(cfg: SolverConfig, s: MapdState) -> jnp.ndarray:
    """Ref tswap.rs:162-168: all tasks used and all agents idle, or horizon."""
    done = jnp.all(s.task_used) & jnp.all(s.phase == AgentPhase.IDLE)
    return done | (s.t > cfg.max_timesteps)


def validate_starts(grid: Grid, starts_idx) -> None:
    """Host-side input validation shared by every solver front door."""
    starts_np = np.asarray(starts_idx)
    if len(np.unique(starts_np)) != len(starts_np):
        raise ValueError("duplicate start cells: agents must be vertex-disjoint")
    if not grid.free.reshape(-1)[starts_np].all():
        raise ValueError("start cell on an obstacle")


def validate_tasks(grid: Grid, tasks) -> None:
    """Reject pickups/deliveries on obstacles — such tasks would otherwise
    pin their agent on an all-INF field and burn the whole solve horizon."""
    tasks_np = np.asarray(tasks)
    if tasks_np.size and not grid.free.reshape(-1)[tasks_np.reshape(-1)].all():
        raise ValueError("task pickup/delivery cell on an obstacle")


def prepare_state_unprimed(cfg: SolverConfig, starts: jnp.ndarray,
                           tasks: jnp.ndarray
                           ) -> Tuple[MapdState, jnp.ndarray]:
    """:func:`prepare_state` minus the field burst: init + pre-loop
    transitions + first assignment.  Callers that cannot run the burst as
    one fused program (see :func:`host_prime_fields`) start here."""
    if tasks.shape[0] == 0:
        tasks = jnp.zeros((1, 2), jnp.int32)
        s = init_state(cfg, starts, 1)
        s = s.replace(task_used=jnp.ones(1, bool))
    else:
        s = init_state(cfg, starts, tasks.shape[0])
    s = _transitions(cfg, s, tasks)
    s = _assign(cfg, s, tasks)
    return s, tasks


@functools.partial(jax.jit, static_argnums=(0, 1))
def _prime_chunk(cfg: SolverConfig, r: int, free: jnp.ndarray,
                 goals: jnp.ndarray) -> jnp.ndarray:
    f = direction_fields(free, goals, max_rounds=cfg.max_sweep_rounds)
    return pack_directions(f.reshape(r, cfg.num_cells))


# Donating the packed-fields buffer halves peak residency (4 GB instead of
# 8 at the 4096^2 rung, where undonated updates still OOM after the
# superseded-buffer fix).  The axon tunnel rejects donation on large fused
# programs (bench.py docs), but this single-scatter program is
# donation-clean — verified at 4 GiB on the real chip.
@functools.partial(jax.jit, donate_argnums=0)
def _prime_update(dirs, rows, fields):
    return dirs.at[rows].set(fields)


def host_prime_fields(cfg: SolverConfig, s: MapdState,
                      free: jnp.ndarray) -> MapdState:
    """The t=0 field burst as a HOST-driven loop of per-chunk device
    programs instead of :func:`prime_fields`'s one fused scan.

    Needed at EXTREME-class grids on the axon tunnel: a single program
    scanning ~100 sweep chunks at (chunk, 4096, 4096) reliably crashes the
    TPU worker (the same fused-multi-step fault class bench.py documents),
    while the identical math dispatched chunk-by-chunk is stable.  The
    jitted chunk programs live at module scope so repeated bursts (e.g.
    bench.py's measure + completion passes) reuse the compiled sweep.
    """
    n, r = cfg.num_agents, min(cfg.replan_chunk, cfg.num_agents)
    nchunks = -(-n // r)
    with trace.span("mapd.host_prime_fields", agents=n, chunks=nchunks):
        for ci in range(nchunks):
            sel = np.clip(np.arange(ci * r, (ci + 1) * r), 0, n - 1)
            sel_j = jnp.asarray(sel, jnp.int32)
            with trace.span("mapd.prime_chunk", chunk=ci):
                fields = _prime_chunk(cfg, r, free, s.goal[sel_j])
                # rebind through s so the superseded dirs reference drops
                # each chunk
                s = s.replace(dirs=_prime_update(s.dirs, s.slot[sel_j],
                                                 fields))
    return s.replace(need_replan=jnp.zeros(cfg.num_agents, bool))


def prepare_state(cfg: SolverConfig, starts: jnp.ndarray, tasks: jnp.ndarray,
                  free: jnp.ndarray) -> Tuple[MapdState, jnp.ndarray]:
    """Initial state ready for stepping: init, first task assignment, and
    the wide-chunk field burst (:func:`prime_fields`).  Returns
    ``(state, tasks)`` with the zero-task case substituted by one pre-used
    dummy task so downstream programs stay shape-total.

    Documented divergence (like the parallel-ordering ones in step.py): an
    agent whose start cell IS its assigned pickup gets its pickup->delivery
    flip from the first ``mapd_step``'s transitions — one step earlier than
    the reference loop (tswap.rs:106-121, where t=0 still records the
    pickup phase) — so makespan can shrink by 1 for such agents and no
    PICKING step is recorded for them.  Collision-freedom is unaffected;
    the makespan-parity suite bounds the effect."""
    s, tasks = prepare_state_unprimed(cfg, starts, tasks)
    return prime_fields(cfg, s, free), tasks


def run_mapd(cfg: SolverConfig, starts: jnp.ndarray, tasks: jnp.ndarray,
             free: jnp.ndarray) -> MapdState:
    """Jittable end-to-end MAPD solve. Returns the final state; makespan is
    ``state.t`` and paths are in ``paths_pos/paths_state[: state.t]``."""
    s, tasks = prepare_state(cfg, starts, tasks, free)

    def cond(s):
        return ~_finished(cfg, s)

    def body(s):
        return mapd_step(cfg, s, tasks, free)

    return jax.lax.while_loop(cond, body, s)


_run_mapd_jit = jax.jit(run_mapd, static_argnums=0)


def solve_offline(grid: Grid, starts_idx: np.ndarray, tasks: np.ndarray,
                  cfg: SolverConfig | None = None) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-facing offline solver (capability of ref tswap_mapd).

    Args:
      grid: the world.
      starts_idx: (N,) flat start cells (distinct).
      tasks: (T, 2) int32 [pickup_idx, delivery_idx].

    Returns:
      (paths_pos (makespan, N), paths_state (makespan, N), makespan).
    """
    if cfg is None:
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=len(starts_idx))
    validate_starts(grid, starts_idx)
    validate_tasks(grid, tasks)
    if len(tasks) == 0:
        n = len(starts_idx)
        return (np.zeros((0, n), np.int32), np.zeros((0, n), np.int8), 0)
    with trace.span("mapd.solve_offline", agents=len(starts_idx),
                    tasks=int(len(tasks))):
        final = _run_mapd_jit(cfg, jnp.asarray(starts_idx, jnp.int32),
                              jnp.asarray(tasks, jnp.int32),
                              jnp.asarray(grid.free))
        makespan = int(final.t)  # the fetch that syncs the device
    if not cfg.record_paths:
        n = len(starts_idx)
        return (np.zeros((0, n), np.int32), np.zeros((0, n), np.int8),
                makespan)
    return (np.asarray(final.paths_pos[:makespan]),
            np.asarray(final.paths_state[:makespan]), makespan)
