"""Checkpoint / resume for MAPD solver state.

The reference has NO persistence at all — every run's state is in-memory
and `reset` wipes it (SURVEY §5: "Checkpoint / resume: None"); the only
export is metrics CSV.  Long solves at the flagship/extreme rungs run for
minutes to hours, so the TPU build provides what the reference lacks: the
full :class:`~p2p_distributed_tswap_tpu.solver.mapd.MapdState` round-trips
through a compressed ``.npz`` archive, and — because the solver is fully
deterministic — a resumed solve is bit-identical to an uninterrupted one
(tests/test_checkpoint.py).

The archive stores plain numpy arrays (one entry per MapdState field plus a
format version), so checkpoints are portable across backends and shardings:
a state saved from a CPU run restores onto TPU, and a restored state can be
``device_put`` onto any mesh with the usual specs.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.ops.distance import packed_cells
from p2p_distributed_tswap_tpu.solver.mapd import MapdState

FORMAT_VERSION = 2
_FIELDS = [f.name for f in dataclasses.fields(MapdState)]
# Fields added by format 2 (stale decentralized view, round 4); a format-1
# archive restores with these at their inert defaults.
_V2_FIELDS = ("vpos", "vgoal", "vstamp", "pend_from", "pend_push")


def _v1_defaults(n: int, pos: np.ndarray, goal: np.ndarray,
                 t: int) -> dict:
    # Seed the view from the archived TRUTH (as if everyone broadcast at
    # the restore step): vgoal must come from the goal array — seeding it
    # from pos would make every mid-route agent look parked-on-goal and
    # trigger spurious Rule-3 swaps on a stale-mode resume.  vstamp is the
    # archived timestep, not zero: a zero stamp under view_ttl_steps would
    # make the freshly-seeded truth view instantly TTL-expired.
    return {
        "vpos": pos.astype(np.int32),
        "vgoal": goal.astype(np.int32),
        "vstamp": np.full(n, t, np.int32),
        "pend_from": np.arange(n, dtype=np.int32),
        "pend_push": np.full(n, -1, np.int32),
    }


def save_state(path: str, state: MapdState, extra: dict | None = None
               ) -> None:
    """Write ``state`` to ``path`` as a compressed npz archive (host-side:
    device arrays are fetched).

    ``extra`` — optional caller metadata (scalars/arrays) stored in the
    SAME archive under reserved ``__x_<key>__`` names, so state and its
    loop latches (step counters, invariant folds, wall-clock ledgers)
    live in one atomically-replaceable file: a sidecar written separately
    can tear from the state on a mid-save kill, which is exactly the
    crash window checkpoints exist for.  Read back with
    :func:`load_extra`."""
    arrays = {name: np.asarray(getattr(state, name)) for name in _FIELDS}
    for k, v in (extra or {}).items():
        arrays[f"__x_{k}__"] = np.asarray(v)
    np.savez_compressed(path, __format_version__=FORMAT_VERSION, **arrays)


def load_extra(path: str) -> dict:
    """Return the ``extra`` dict stored by :func:`save_state` (empty if
    none was stored)."""
    out = {}
    with np.load(path) as z:
        for name in z.files:
            if name.startswith("__x_") and name.endswith("__"):
                out[name[4:-2]] = z[name]
    return out


def load_state(path: str, cfg: SolverConfig | None = None,
               expected_num_tasks: int | None = None) -> MapdState:
    """Restore a :class:`MapdState` saved by :func:`save_state`.

    Pass the ``cfg`` the state will be stepped under to fail fast on a
    mismatch (wrong agent count, grid size, path recording) instead of an
    opaque shape error — or silently wrong gathers — deep inside the
    jitted step.  Pass ``expected_num_tasks`` (``tasks.shape[0]`` of the
    array the resumed solve will step with) to catch a tasks/checkpoint
    mismatch: ``task_used``'s length comes from the checkpoint, so stepping
    with a different tasks array mis-indexes inside jit (wrong gathers,
    not a shape error)."""
    with np.load(path) as z:
        if "__format_version__" not in z:
            raise ValueError(
                f"{path} is not a solver checkpoint (no format version)")
        version = int(z["__format_version__"])
        if version not in (1, FORMAT_VERSION):
            raise ValueError(
                f"checkpoint format {version} != supported {FORMAT_VERSION}")
        required = [n for n in _FIELDS
                    if not (version == 1 and n in _V2_FIELDS)]
        missing = [n for n in required if n not in z]
        if missing:
            raise ValueError(f"checkpoint missing fields: {missing}")
        arrays = {name: z[name] for name in required}
        if version == 1:
            arrays.update(_v1_defaults(arrays["pos"].shape[0],
                                       arrays["pos"], arrays["goal"],
                                       int(arrays["t"])))
        state = MapdState(**{name: jnp.asarray(arrays[name])
                             for name in _FIELDS})
    if cfg is not None:
        n = state.pos.shape[0]
        if n != cfg.num_agents:
            raise ValueError(
                f"checkpoint has {n} agents, config expects "
                f"{cfg.num_agents}")
        if state.dirs.shape != (n, packed_cells(cfg.num_cells)):
            raise ValueError(
                f"checkpoint field shape {state.dirs.shape} does not match "
                f"a {cfg.height}x{cfg.width} grid "
                f"({(n, packed_cells(cfg.num_cells))} expected)")
        want_tdim = cfg.max_timesteps + 1 if cfg.record_paths else 1
        if state.paths_pos.shape[0] != want_tdim:
            raise ValueError(
                f"checkpoint path buffer has {state.paths_pos.shape[0]} "
                f"rows, config (record_paths={cfg.record_paths}, "
                f"max_timesteps={cfg.max_timesteps}) expects {want_tdim}")
    if expected_num_tasks is not None:
        t = state.task_used.shape[0]
        if t != expected_num_tasks:
            raise ValueError(
                f"checkpoint was saved against {t} tasks, resumed solve "
                f"steps with {expected_num_tasks} — same tasks array "
                f"required for a valid resume")
    return state
