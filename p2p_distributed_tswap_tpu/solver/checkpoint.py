"""Checkpoint / resume for MAPD solver state.

The reference has NO persistence at all — every run's state is in-memory
and `reset` wipes it (SURVEY §5: "Checkpoint / resume: None"); the only
export is metrics CSV.  Long solves at the flagship/extreme rungs run for
minutes to hours, so the TPU build provides what the reference lacks: the
full :class:`~p2p_distributed_tswap_tpu.solver.mapd.MapdState` round-trips
through a compressed ``.npz`` archive, and — because the solver is fully
deterministic — a resumed solve is bit-identical to an uninterrupted one
(tests/test_checkpoint.py).

The archive stores plain numpy arrays (one entry per MapdState field plus a
format version), so checkpoints are portable across backends and shardings:
a state saved from a CPU run restores onto TPU, and a restored state can be
``device_put`` onto any mesh with the usual specs.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from p2p_distributed_tswap_tpu.solver.mapd import MapdState

FORMAT_VERSION = 1
_FIELDS = [f.name for f in dataclasses.fields(MapdState)]


def save_state(path: str, state: MapdState) -> None:
    """Write ``state`` to ``path`` as a compressed npz archive (host-side:
    device arrays are fetched)."""
    arrays = {name: np.asarray(getattr(state, name)) for name in _FIELDS}
    np.savez_compressed(path, __format_version__=FORMAT_VERSION, **arrays)


def load_state(path: str) -> MapdState:
    """Restore a :class:`MapdState` saved by :func:`save_state`."""
    with np.load(path) as z:
        version = int(z["__format_version__"])
        if version != FORMAT_VERSION:
            raise ValueError(
                f"checkpoint format {version} != supported {FORMAT_VERSION}")
        missing = [n for n in _FIELDS if n not in z]
        if missing:
            raise ValueError(f"checkpoint missing fields: {missing}")
        return MapdState(**{name: jnp.asarray(z[name]) for name in _FIELDS})
