"""On-device solve-certification invariants.

The reference's termination contract (src/algorithm/tswap.rs:162-168) implies
— but never checks — that every recorded step is a valid MAPF transition.
For the big benchmark rungs a throughput number alone cannot distinguish a
correct solver from one that spins agents in place or teleports them, so the
certification runs fold this check into the solve: a single device-resident
bool, AND-ed every step and fetched once at the end (VERDICT r2 weak item 1).

Checked per transition ``prev_pos -> pos``:

- **vertex-disjointness** — no two agents share a cell (TSWAP's core
  guarantee, ref tswap.rs:254-257);
- **unit moves** — every agent stays or moves to a 4-neighbor;
- **on-grid legality** — every agent sits on a free cell.

Deliberately NOT checked: pairwise edge exchange.  Mutual position swaps
are a sanctioned TSWAP mechanism — the reference's in-pass mutual-swap
move (tswap.rs:269-278) and this build's movement phase
(solver/step.py) both physically exchange an adjacent deadlocked pair,
and the push extension resolves shared-delivery deadlocks through
exactly such a swap.

Cost: O(N log N) sort — microseconds next to a solve step; safe to run
every step.
"""

from __future__ import annotations

import jax.numpy as jnp

from p2p_distributed_tswap_tpu.core.config import SolverConfig


def step_invariants(cfg: SolverConfig, prev_pos: jnp.ndarray,
                    pos: jnp.ndarray, free: jnp.ndarray) -> jnp.ndarray:
    """() bool: True iff the transition ``prev_pos -> pos`` is a legal
    collision-free MAPF step (see module docstring).  Jit-friendly; fold
    results with ``&`` and fetch once."""
    n, w = cfg.num_agents, cfg.width

    sp = jnp.sort(pos)
    distinct = jnp.all(sp[1:] != sp[:-1]) if n > 1 else jnp.bool_(True)

    dx = jnp.abs(pos % w - prev_pos % w)
    dy = jnp.abs(pos // w - prev_pos // w)
    unit = jnp.all(dx + dy <= 1)

    on_free = jnp.all(free.reshape(-1)[pos])

    return distinct & unit & on_free
