"""Sequential TSWAP oracle — the executable spec for parity tests.

This is a pure-Python/numpy transcription of the *semantics* of the reference's
offline solver (``tswap_mapd`` + ``tswap_step`` + ``get_path``,
src/algorithm/tswap.rs:39-390).  It is TEST ORACLE code, not product code
(SURVEY §7 layer 3): the batched TPU solver is validated against it for
collision-freedom and makespan, never the other way around.

Documented deviations from the reference (shared with the TPU solver so the
two remain comparable):

1. Next-hop selection descends an exact BFS distance-to-goal field with
   first-minimum tie-breaking in the reference's neighbor order
   ``[(0,1),(1,0),(0,-1),(-1,0)]`` (src/algorithm/tswap.rs:62), instead of
   replaying A* heap order (src/algorithm/tswap.rs:288-390).  Both always step
   along *a* shortest path; only equal-length tie-breaks differ.
2. On an unreachable goal the agent waits, where the reference takes one
   greedy Manhattan step if strictly improving (src/algorithm/tswap.rs:378-389).
   Irrelevant on connected grids (all shipped generators guarantee this).

Everything else is step-for-step: Rule 1 stay-at-goal, Rule 3 goal swap with
an at-goal blocker, Rule 4 deadlock-chain walk with abort-on-revisit and goal
rotation, the sequential movement pass with mutual position swaps (including
the reference's quirk that a swap-moved agent can move again later in the same
pass), greedy nearest-pickup task assignment in agent-id order, the
Idle -> ToPickup -> ToDelivery machine, and the t > max_timesteps cutoff.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from p2p_distributed_tswap_tpu.core.agent import AgentPhase, AgentState
from p2p_distributed_tswap_tpu.core.grid import Grid

_NEIGHBOR_ORDER = ((0, 1), (1, 0), (0, -1), (-1, 0))  # (dx, dy)
_INF = 1 << 30


class OracleSim:
    """Sequential MAPD/TSWAP simulator over flat cell indices."""

    def __init__(self, grid: Grid, starts_idx: np.ndarray, tasks: np.ndarray,
                 max_timesteps: int = 2000):
        self.grid = grid
        self.free = grid.free
        self.h, self.w = grid.free.shape
        self.n = len(starts_idx)
        self.v = np.array(starts_idx, dtype=np.int64)  # current cell per agent
        self.g = self.v.copy()                         # goal cell per agent
        self.tasks = np.array(tasks, dtype=np.int64)   # (T, 2) pickup, delivery
        self.task_used = np.zeros(len(tasks), dtype=bool)
        self.agent_task: List[Optional[int]] = [None] * self.n
        self.phase = np.full(self.n, AgentPhase.IDLE, dtype=np.int64)
        self.max_timesteps = max_timesteps
        self.t = 0
        self.paths: List[List[Tuple[int, int]]] = [[] for _ in range(self.n)]
        self._dist_cache: Dict[int, np.ndarray] = {}
        assert len(np.unique(self.v)) == self.n, "duplicate start cells"

    # -- pathfinding (BFS field descent; deviation 1 above) ------------------

    def _dist_field(self, goal: int) -> np.ndarray:
        cached = self._dist_cache.get(goal)
        if cached is not None:
            return cached
        dist = np.full(self.h * self.w, _INF, dtype=np.int64)
        gy, gx = divmod(goal, self.w)
        if self.free[gy, gx]:
            dist[goal] = 0
            q = deque([goal])
            while q:
                c = q.popleft()
                cy, cx = divmod(c, self.w)
                for dx, dy in _NEIGHBOR_ORDER:
                    ny, nx = cy + dy, cx + dx
                    if 0 <= ny < self.h and 0 <= nx < self.w and self.free[ny, nx]:
                        nc = ny * self.w + nx
                        if dist[nc] > dist[c] + 1:
                            dist[nc] = dist[c] + 1
                            q.append(nc)
        self._dist_cache[goal] = dist
        return dist

    def next_hop(self, v: int, g: int) -> Optional[int]:
        """First cell after ``v`` on a shortest path to ``g`` (= path[1] of the
        reference's get_path); None when at goal or unreachable."""
        if v == g:
            return None
        dist = self._dist_field(g)
        if dist[v] >= _INF:
            return None
        vy, vx = divmod(v, self.w)
        best, best_d = None, dist[v]
        for dx, dy in _NEIGHBOR_ORDER:
            ny, nx = vy + dy, vx + dx
            if 0 <= ny < self.h and 0 <= nx < self.w:
                nc = ny * self.w + nx
                if dist[nc] < best_d:
                    best, best_d = nc, dist[nc]
        return best

    # -- one TSWAP step (ref tswap_step, src/algorithm/tswap.rs:174-286) -----

    def tswap_step(self) -> None:
        n, v, g = self.n, self.v, self.g

        def occupant(cell: int) -> Optional[int]:
            """First agent at ``cell`` (ref agents.iter().position, :192)."""
            hits = np.nonzero(v == cell)[0]
            return int(hits[0]) if len(hits) else None

        # --- goal-swapping phase (Rules 1, 3, 4; ref :180-252) ---
        for i in range(n):
            if v[i] == g[i]:
                continue  # Rule 1
            u = self.next_hop(v[i], g[i])
            if u is None:
                continue
            j = occupant(u)
            if j is None or j == i:
                continue
            if v[j] == g[j]:
                # Rule 3: blocker parked on its goal -> swap goals (:197-202)
                g[i], g[j] = g[j], g[i]
            else:
                # Rule 4: walk the blocking chain (:204-238)
                a_p = [i]
                cur = j
                deadlock = False
                while True:
                    if v[cur] == g[cur]:
                        break
                    wh = self.next_hop(v[cur], g[cur])
                    if wh is None:
                        break
                    c = occupant(wh)
                    if c is None:
                        break
                    if cur in a_p:
                        a_p = []
                        break  # revisit that is not a cycle through i: abort
                    a_p.append(cur)
                    cur = c
                    if cur == i:
                        deadlock = True
                        break
                if deadlock and len(a_p) > 1:
                    # rotate goals backward along the cycle (:241-248)
                    last_goal = g[a_p[-1]]
                    for k in range(len(a_p) - 1, 0, -1):
                        g[a_p[k]] = g[a_p[k - 1]]
                    g[a_p[0]] = last_goal

        # --- movement phase (Rules 2, 5, mutual swap; ref :257-285) ---
        for i in range(n):
            if v[i] == g[i]:
                continue
            u = self.next_hop(v[i], g[i])
            if u is None:
                continue
            j = occupant(u)
            if j is not None:
                if i != j:
                    uj = self.next_hop(v[j], g[j])
                    if uj is not None and uj == v[i]:
                        v[i], v[j] = v[j], v[i]  # mutual position swap (:274-278)
                    # else Rule 5: stay
            else:
                v[i] = u  # Rule 2

    # -- MAPD loop (ref tswap_mapd, src/algorithm/tswap.rs:104-170) ----------

    def run(self) -> int:
        """Run to completion; returns the makespan (number of recorded steps)."""
        while True:
            self.step_mapd()
            if self.finished():
                return self.t

    def step_mapd(self) -> None:
        v, g = self.v, self.g
        for i in range(self.n):
            # arrival transitions (:106-121)
            if v[i] == g[i]:
                if self.phase[i] == AgentPhase.TO_PICKUP:
                    self.phase[i] = AgentPhase.TO_DELIVERY
                    g[i] = self.tasks[self.agent_task[i]][1]
                elif self.phase[i] == AgentPhase.TO_DELIVERY:
                    self.phase[i] = AgentPhase.IDLE
                    self.agent_task[i] = None
            # greedy nearest-pickup assignment (:123-138)
            if self.phase[i] == AgentPhase.IDLE:
                unused = np.nonzero(~self.task_used)[0]
                if len(unused):
                    py, px = divmod(v[i], self.w)
                    d = (np.abs(self.tasks[unused, 0] % self.w - px)
                         + np.abs(self.tasks[unused, 0] // self.w - py))
                    k = unused[int(np.argmin(d))]  # first min = lowest task idx
                    self.task_used[k] = True
                    self.agent_task[i] = int(k)
                    self.phase[i] = AgentPhase.TO_PICKUP
                    g[i] = self.tasks[k][0]

        self.tswap_step()

        # record paths (:143-158)
        for i in range(self.n):
            if self.phase[i] == AgentPhase.IDLE:
                s = AgentState.IDLE
            elif self.phase[i] == AgentPhase.TO_PICKUP:
                s = AgentState.PICKING
            elif v[i] == g[i]:
                s = AgentState.DELIVERED
            else:
                s = AgentState.CARRYING
            self.paths[i].append((int(v[i]), int(s)))
        self.t += 1

    def finished(self) -> bool:
        return (bool(self.task_used.all())
                and bool((self.phase == AgentPhase.IDLE).all())) \
            or self.t > self.max_timesteps

    # -- invariants ----------------------------------------------------------

    def assert_no_collisions(self) -> None:
        assert len(np.unique(self.v)) == self.n, "vertex collision"
