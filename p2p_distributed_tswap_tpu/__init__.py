"""p2p_distributed_tswap_tpu — a TPU-native framework for large-scale Multi-Agent
Pickup and Delivery (MAPD) with the TSWAP target-swapping algorithm.

This is a ground-up JAX/XLA/Pallas redesign of the capabilities of the reference
system ``RenKoya1/p2p_distributed_tswap`` (a Rust + libp2p process fleet): the
per-agent A* + message-passing solver becomes a batched kernel over dense agent /
grid tensors, sharded across TPU chips with ``shard_map`` and ICI collectives,
while a native C++ host runtime (under ``cpp/``) reproduces the reference's
manager/agent process roles, pub/sub wire protocol, operator CLI, and CSV metrics.

Package layout
--------------
- ``core``     — domain model: grids, map IO, tasks, sampling, config (ref ``src/map/``)
- ``ops``      — array kernels: BFS distance / direction fields (fast-sweeping scans)
- ``solver``   — TSWAP step kernels + offline MAPD loop (ref ``src/algorithm/``)
- ``parallel`` — device meshes, shard_map solver, collectives
- ``metrics``  — task / path / network metrics with reference-compatible CSV schemas
- ``runtime``  — Python side of the host runtime (bus client, solver daemon)
- ``models``   — benchmark scenario/config ladder (flagship configs)
"""

__version__ = "0.1.0"

from p2p_distributed_tswap_tpu.core.grid import Grid  # noqa: F401
from p2p_distributed_tswap_tpu.core.tasks import Task, TaskGenerator  # noqa: F401
