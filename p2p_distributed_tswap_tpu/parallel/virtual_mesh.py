"""Virtual CPU mesh bootstrap (shared by tests/conftest.py and
__graft_entry__.dryrun_multichip).

Multi-chip TPU hardware is not available in CI; sharded code runs on
``xla_force_host_platform_device_count=N`` virtual CPU devices, which
exercise the same SPMD partitioner and collectives as a real mesh.  The CPU
client is created lazily by jax, so the flag takes effect as long as it is
written before the first ``jax.devices("cpu")`` call — even if jax itself is
already imported (this environment's sitecustomize imports jax at interpreter
startup with ``JAX_PLATFORMS=axon``).

This module deliberately imports nothing heavier than ``os``/``re`` at top
level so callers can invoke :func:`force_virtual_cpu_devices` before their
first jax import.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def force_virtual_cpu_devices(n_devices: int) -> None:
    """Ensure ``XLA_FLAGS`` requests at least ``n_devices`` virtual CPU
    devices.  A preset smaller count is raised to ``n_devices``; a preset
    equal-or-larger count is kept.  Must run before jax creates its CPU
    client; a no-op afterwards (jax caches the client)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"--{_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --{_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = (
            flags[:m.start()] + f"--{_FLAG}={n_devices}" + flags[m.end():])


def pin_cpu_backend(n_devices: int):
    """Route the current process onto the virtual CPU backend and return its
    devices: force the device count, restrict platform resolution to CPU
    (best effort — harmless if a backend was already chosen), and pin
    ``jax_default_device`` to CPU so no op ever touches a (possibly broken)
    accelerator plugin.

    NOTE: this is terminal for the process's backend selection — after it
    runs, the default device is CPU and ``JAX_PLATFORMS``/``XLA_FLAGS`` stay
    mutated (they also leak to spawned subprocesses).  Intended for dedicated
    dryrun/test processes, not for code sharing a process with real-TPU work.
    """
    force_virtual_cpu_devices(n_devices)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    devices = jax.devices("cpu")  # never query the default backend
    assert len(devices) >= n_devices, (
        f"need {n_devices} devices, have {len(devices)}")
    jax.config.update("jax_default_device", devices[0])
    return devices
