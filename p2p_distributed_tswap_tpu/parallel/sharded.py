"""Agent-axis sharded TSWAP solver (shard_map + ICI collectives).

This is the TPU-native replacement for the reference's scale-out story
(SURVEY §2 strategy table): where the reference runs one OS process per agent
and floods every position update over a gossipsub mesh (O(N^2) messages,
DECENTRALIZED_ISSUES.md:21-25), here the **direction fields** — O(N * H * W)
bytes, the only state — are sharded across devices by field row, and each
step exchanges exactly O(N) bytes over ICI:

- ``pos/goal/slot/phase`` (a few int32 per agent) are replicated; every device
  runs the identical deterministic rule phases, so no collective is needed for
  conflict resolution.
- The per-agent next-hop lookup ``dirs[slot[i], pos[i]]`` is the one truly
  distributed access (an agent's field row can live on any device).  Each
  device reads the rows it owns for whichever agents hold them and a single
  ``psum`` assembles the (N,) direction-code vector — the moral equivalent of
  the reference's "broadcast position, receive neighbor positions" tick
  (src/bin/decentralized/agent.rs:730-789) at 1 byte per agent per hop.
- Replanning shards naturally: each device recomputes only field rows it owns
  (fast-sweeping over its own (R, H, W) batch) — the proposed-but-never-built
  geographic partitioning of the reference (DECENTRALIZED_ISSUES.md:62-96),
  realized as data parallelism over fields.

``num_agents`` must be divisible by the mesh size (pad with parked agents at
distinct free cells if needed).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.ops.distance import (
    apply_direction,
    direction_fields,
    gather_packed,
    pack_directions,
    packed_cells,
)
from p2p_distributed_tswap_tpu.parallel.mesh import (AGENTS_AXIS,
    agent_mesh, shard_map)
from p2p_distributed_tswap_tpu.solver import mapd as mapd_mod
from p2p_distributed_tswap_tpu.solver.mapd import MapdState, init_state


def _sharded_next_hops(cfg: SolverConfig, dirs_local: jnp.ndarray,
                       slot: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """Distributed ``dirs[slot[i], pos[i]]``: one psum of (N,) int32."""
    n = cfg.num_agents
    rows_local = dirs_local.shape[0]
    shard = jax.lax.axis_index(AGENTS_AXIS)
    # inverse of the slot permutation: which agent holds each field row
    inv = jnp.zeros(n, jnp.int32).at[slot].set(jnp.arange(n, dtype=jnp.int32))
    rows = jnp.arange(rows_local, dtype=jnp.int32)
    holders = inv[shard * rows_local + rows]          # (L,) agent per local row
    vals = gather_packed(dirs_local, rows, pos[holders])  # (L,) uint8 codes
    contrib = jnp.zeros(n, jnp.int32).at[holders].set(vals.astype(jnp.int32))
    codes = jax.lax.psum(contrib, AGENTS_AXIS).astype(jnp.uint8)
    return apply_direction(pos, codes, cfg.width)


def _sharded_prime(cfg: SolverConfig, s: MapdState, free: jnp.ndarray
                   ) -> MapdState:
    """The t=0 field burst, sharded: every device computes ALL field rows it
    owns in WIDE ``replan_chunk`` batches (one fixed-trip lax.scan) — the
    distributed twin of mapd.prime_fields.  Hoisting the burst out of the
    per-step loop is what lets the steady-state replan below run the NARROW
    chunk: with the wide chunk in the loop, every step at scale pays a
    ~wide-sweep's worth of wasted width for a handful of dirty rows
    (VERDICT r2 weak item 3; measured 152 vs 328 ms/step single-device)."""
    n = cfg.num_agents
    dirs_local = s.dirs
    rows_local = dirs_local.shape[0]
    shard = jax.lax.axis_index(AGENTS_AXIS)
    # which agent holds each of my field rows (inverse slot permutation)
    inv = jnp.zeros(n, jnp.int32).at[s.slot].set(
        jnp.arange(n, dtype=jnp.int32))
    r = min(cfg.replan_chunk, rows_local)
    nchunks = -(-rows_local // r)
    lane = jnp.arange(r, dtype=jnp.int32)

    def chunk(dirs_local, ci):
        row_local = jnp.clip(ci * r + lane, 0, rows_local - 1)
        holder = inv[shard * rows_local + row_local]
        fields = direction_fields(free, s.goal[holder],
                                  max_rounds=cfg.max_sweep_rounds)
        dirs_local = dirs_local.at[row_local].set(
            pack_directions(fields.reshape(r, cfg.num_cells)))
        return dirs_local, None

    dirs_local, _ = jax.lax.scan(chunk, dirs_local,
                                 jnp.arange(nchunks, dtype=jnp.int32))
    return s.replace(dirs=dirs_local,
                     need_replan=jnp.zeros_like(s.need_replan))


def _sharded_replan(cfg: SolverConfig, s: MapdState, free: jnp.ndarray
                    ) -> MapdState:
    """Each device recomputes the stale field rows it owns; drains fully.
    Narrow steady-state chunk — the t=0 burst goes through _sharded_prime."""
    n = cfg.num_agents
    dirs_local = s.dirs
    rows_local = dirs_local.shape[0]
    shard = jax.lax.axis_index(AGENTS_AXIS)
    idx = jnp.arange(n, dtype=jnp.int32)
    r = min(cfg.replan_chunk_small, n)
    own = s.need_replan & (s.slot // rows_local == shard)

    def cond(carry):
        _, own = carry
        return jnp.any(own)

    def body(carry):
        dirs_local, own = carry
        priority = jnp.where(own, idx, n)
        sel = -jax.lax.top_k(-priority, r)[0]
        valid = sel < n
        selc = jnp.clip(sel, 0, n - 1)
        fields = direction_fields(free, s.goal[selc],
                                  max_rounds=cfg.max_sweep_rounds)
        fields = pack_directions(fields.reshape(r, cfg.num_cells))
        # local row index; invalid lanes go to a scratch row (no OOB scatter)
        local_row = jnp.where(valid, s.slot[selc] - shard * rows_local,
                              rows_local)
        padded = jnp.concatenate(
            [dirs_local,
             jnp.zeros((1, packed_cells(cfg.num_cells)), dirs_local.dtype)])
        dirs_local = padded.at[local_row].set(fields)[:rows_local]
        cleared = jnp.zeros(n, bool).at[selc].max(valid)
        return dirs_local, own & ~cleared

    dirs_local, _ = jax.lax.while_loop(cond, body, (dirs_local, own))
    # every stale row is owned by exactly one device, so the union drains all
    return s.replace(dirs=dirs_local,
                     need_replan=jnp.zeros_like(s.need_replan))


def _nh_factory(cfg: SolverConfig, dirs_local: jnp.ndarray):
    return functools.partial(_sharded_next_hops, cfg, dirs_local)


def sharded_mapd_step(cfg: SolverConfig, s: MapdState, tasks: jnp.ndarray,
                      free: jnp.ndarray) -> MapdState:
    """One MAPD timestep inside shard_map: the single-device MAPD sequencing
    (mapd.mapd_step) with the distributed replan and next-hop lookup swapped
    in — replicated control flow, sharded fields."""
    return mapd_mod.mapd_step(cfg, s, tasks, free,
                              replan_fn=_sharded_replan,
                              nh_factory=_nh_factory)


def agent_state_specs() -> MapdState:
    """shard_map partition specs for MapdState on the 1-D agent mesh: only
    the direction-field rows shard (the dominant buffer); every (N,) vector
    and the stale-view fields are replicated (they feed replicated rule
    phases).  Single source of truth for every 1-D-mesh entry point
    (__graft_entry__, analysis/sharded_steptime.py)."""
    return MapdState(
        pos=P(), goal=P(), slot=P(), dirs=P(AGENTS_AXIS, None), phase=P(),
        agent_task=P(), task_used=P(), need_replan=P(), t=P(),
        paths_pos=P(), paths_state=P(),
        vpos=P(), vgoal=P(), vstamp=P(), pend_from=P(), pend_push=P())


def make_sharded_runner(cfg: SolverConfig, mesh: Mesh | None = None,
                        num_tasks: int | None = None):
    """Build a jitted sharded end-to-end MAPD solve over ``mesh``.

    Returns ``run(starts (N,), tasks (T,2), free (H,W)) -> MapdState``.
    """
    if mesh is None:
        mesh = agent_mesh()
    n_dev = mesh.devices.size
    assert cfg.num_agents % n_dev == 0, (
        f"num_agents={cfg.num_agents} must divide over {n_dev} devices")

    state_specs = agent_state_specs()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(state_specs, P(), P()), out_specs=state_specs,
        check_vma=False)
    def run_shard(s, tasks, free):
        s = _sharded_prime(cfg, s, free)  # wide t=0 burst, off the hot loop

        def cond(s):
            return ~mapd_mod._finished(cfg, s)

        def body(s):
            return sharded_mapd_step(cfg, s, tasks, free)

        return jax.lax.while_loop(cond, body, s)

    @jax.jit
    def run(starts, tasks, free):
        if tasks.shape[0] == 0:
            # same trace-safety device as mapd.run_mapd: one pre-used dummy
            tasks = jnp.zeros((1, 2), jnp.int32)
            s = init_state(cfg, starts, 1)
            s = s.replace(task_used=jnp.ones(1, bool))
        else:
            s = init_state(cfg, starts, tasks.shape[0])
        # pre-loop transitions + first assignment, matching
        # mapd.prepare_state's ordering (an agent starting ON its assigned
        # pickup flips to delivery in the first step's transitions) so
        # sharded runs stay bit-identical to the single-device solver;
        # both are replicated ops, no collectives needed
        s = mapd_mod._transitions(cfg, s, tasks)
        s = mapd_mod._assign(cfg, s, tasks)
        return run_shard(s, tasks, free)

    return run


def solve_offline_sharded(grid: Grid, starts_idx: np.ndarray,
                          tasks: np.ndarray, cfg: SolverConfig | None = None,
                          mesh: Mesh | None = None
                          ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Sharded counterpart of mapd.solve_offline (same contract)."""
    if cfg is None:
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=len(starts_idx))
    mapd_mod.validate_starts(grid, starts_idx)
    mapd_mod.validate_tasks(grid, tasks)
    run = make_sharded_runner(cfg, mesh)
    final = run(jnp.asarray(starts_idx, jnp.int32),
                jnp.asarray(tasks, jnp.int32), jnp.asarray(grid.free))
    makespan = int(final.t)
    if not cfg.record_paths:
        n = len(starts_idx)
        return (np.zeros((0, n), np.int32), np.zeros((0, n), np.int8),
                makespan)
    return (np.asarray(final.paths_pos[:makespan]),
            np.asarray(final.paths_state[:makespan]), makespan)
