"""Device-mesh helpers.

The framework's scale-out axis is the **agent axis** (SURVEY §2: the reference
parallelizes per-agent across OS processes; the TPU-native analog is sharding
the agent/field tensors over a ``jax.sharding.Mesh`` and exchanging the few
bytes of cross-shard state over ICI collectives instead of gossipsub
broadcast).  Direction fields — the memory- and FLOP-heavy state — live
sharded by field row; the small (N,) control vectors stay replicated.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AGENTS_AXIS = "agents"
TILES_AXIS = "tiles"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """Version-portable ``shard_map``: the stable ``jax.shard_map``
    (``check_vma``) when this jax has it, else the long-standing
    ``jax.experimental.shard_map`` (same semantics; the replication
    check there is spelled ``check_rep``).  Every mesh entry point in
    this repo — the offline sharded solvers, the tiled sweeps, and the
    mesh solverd serving path — routes through here, so a jax upgrade
    or downgrade never strands the whole sharding stack again (this
    container's jax 0.4.x is exactly that case)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm

    return legacy_sm(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name) -> int:
    """Mesh-axis size inside shard_map, version-portable: the stable
    ``jax.lax.axis_size`` when present, else ``lax.psum(1, axis)`` —
    which constant-folds to a concrete Python int on every jax that
    lacks the named accessor (verified on this container's 0.4.x)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _default_devices(n_devices: int | None = None):
    default = jax.config.jax_default_device
    devices = (jax.devices(default.platform) if default is not None
               else jax.devices())
    return devices if n_devices is None else devices[:n_devices]


def agent_tile_mesh(n_agent_shards: int, n_tiles: int,
                    devices=None) -> Mesh:
    """2-D (agents x tiles) mesh: field ROWS shard over the agents axis and
    each row's cells (grid bands) over the tiles axis — the composition
    used for grids/fleets past one chip's field budget (SCALING.md)."""
    if devices is None:
        devices = _default_devices(n_agent_shards * n_tiles)
    assert len(devices) >= n_agent_shards * n_tiles
    return Mesh(
        np.array(devices[:n_agent_shards * n_tiles]).reshape(
            n_agent_shards, n_tiles),
        (AGENTS_AXIS, TILES_AXIS))


def agent_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the agent axis.

    Defaults to all devices of the *default-device* platform when
    ``jax_default_device`` is set (so a CPU-forced test session gets the
    virtual CPU mesh even though a TPU plugin is also registered), else all
    visible devices.
    """
    if devices is None:
        default = jax.config.jax_default_device
        devices = (jax.devices(default.platform) if default is not None
                   else jax.devices())
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AGENTS_AXIS,))


def field_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for (N, H*W) direction fields: rows split over devices."""
    return NamedSharding(mesh, P(AGENTS_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
