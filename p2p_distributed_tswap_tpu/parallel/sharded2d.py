"""2-D (agents x tiles) sharded MAPD solver — the EXTREME deployment shape.

Composes the framework's two shardings (SCALING.md):

- **agents axis** (parallel/sharded.py): direction-field ROWS shard across
  one mesh dimension — each device block owns N / A field rows.
- **tiles axis** (ops/tiled_distance.py): each field row's CELLS shard
  across the other dimension as horizontal grid bands — each device holds
  (N/A rows) x (H/T band), so per-device field residency shrinks by the
  full mesh size A*T, and the sweep's transient workspace by T.

Control state (pos/goal/slot/phase, a few int32 per agent) stays replicated;
every device runs the identical deterministic rule phases.  The two
distributed pieces per step:

- **next-hop lookup** ``dirs[slot[i], pos[i] nibble]``: the device holding
  both agent i's field row (agents axis) and the band containing ``pos[i]``
  (tiles axis) contributes the code; a single psum over BOTH axes assembles
  the replicated (N,) vector — still O(N) bytes over ICI per step.
- **replanning**: all devices of an agent block select the same stale rows
  (replicated inputs, deterministic top-k); each computes its own BAND of
  the new fields with the halo-exchanged tiled sweep
  (ops/tiled_distance.tiled_direction_fields over the tiles axis) and
  writes its (rows x band) block.

Results are bit-identical to the single-device solver
(tests/test_sharded2d.py) — sharding is purely a capacity/bandwidth lever.

Constraints: ``num_agents % A == 0``, ``H % T == 0``, and the per-band cell
count ``(H/T) * W`` must be a multiple of 8 (whole packed uint32 words per
band; true whenever W is a multiple of 8).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from p2p_distributed_tswap_tpu.core.config import SolverConfig
from p2p_distributed_tswap_tpu.core.grid import Grid
from p2p_distributed_tswap_tpu.ops.distance import (
    apply_direction,
    pack_directions,
)
from p2p_distributed_tswap_tpu.ops.tiled_distance import (
    tiled_direction_fields,
)
from p2p_distributed_tswap_tpu.parallel.mesh import (
    AGENTS_AXIS,
    TILES_AXIS,
    agent_tile_mesh,
    shard_map,
)
from p2p_distributed_tswap_tpu.solver import mapd as mapd_mod
from p2p_distributed_tswap_tpu.solver.mapd import MapdState, init_state


def _next_hops_2d(cfg: SolverConfig, dirs_local: jnp.ndarray,
                  slot: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """Distributed ``dirs[slot[i], pos[i]]`` on the 2-D mesh: one psum over
    (agents, tiles) of an (N,) int32 contribution vector."""
    n = cfg.num_agents
    rows_local, words_local = dirs_local.shape
    a_shard = jax.lax.axis_index(AGENTS_AXIS)
    t_shard = jax.lax.axis_index(TILES_AXIS)
    # which agent holds each of my field rows (inverse slot permutation)
    inv = jnp.zeros(n, jnp.int32).at[slot].set(jnp.arange(n, dtype=jnp.int32))
    rows = jnp.arange(rows_local, dtype=jnp.int32)
    holders = inv[a_shard * rows_local + rows]        # (L,) agent per row
    p = pos[holders]
    word_global = p >> 3
    in_band = ((word_global >= t_shard * words_local)
               & (word_global < (t_shard + 1) * words_local))
    word = dirs_local[rows, jnp.clip(word_global - t_shard * words_local,
                                     0, words_local - 1)]
    code = (word >> ((p & 7) * 4).astype(jnp.uint32)) & 0xF
    contrib = jnp.zeros(n, jnp.int32).at[holders].set(
        jnp.where(in_band, code.astype(jnp.int32), 0))
    codes = jax.lax.psum(contrib, (AGENTS_AXIS, TILES_AXIS)).astype(jnp.uint8)
    return apply_direction(pos, codes, cfg.width)


def _prime_2d(cfg: SolverConfig, s: MapdState, free_local: jnp.ndarray
              ) -> MapdState:
    """The t=0 field burst on the 2-D mesh: every agent block computes ALL
    its rows in WIDE ``replan_chunk`` batches, each tiles-axis device
    sweeping its band (see parallel/sharded.py::_sharded_prime for why the
    burst is hoisted off the steady-state path).  The trip count
    ceil(rows_local / r) is identical on every device — rows_local is
    uniform — so the tiled sweep's collective schedule lines up with no
    pmax needed."""
    n = cfg.num_agents
    dirs_local = s.dirs
    rows_local, words_local = dirs_local.shape
    a_shard = jax.lax.axis_index(AGENTS_AXIS)
    inv = jnp.zeros(n, jnp.int32).at[s.slot].set(
        jnp.arange(n, dtype=jnp.int32))
    r = min(cfg.replan_chunk, rows_local)
    nchunks = -(-rows_local // r)
    lane = jnp.arange(r, dtype=jnp.int32)

    def chunk(dirs_local, ci):
        row_local = jnp.clip(ci * r + lane, 0, rows_local - 1)
        holder = inv[a_shard * rows_local + row_local]
        fields = tiled_direction_fields(
            free_local, s.goal[holder], cfg.width, axis_name=TILES_AXIS,
            max_rounds=cfg.max_sweep_rounds,
            fixpoint_axes=(AGENTS_AXIS, TILES_AXIS))
        dirs_local = dirs_local.at[row_local].set(
            pack_directions(fields.reshape(r, -1)))
        return dirs_local, None

    dirs_local, _ = jax.lax.scan(chunk, dirs_local,
                                 jnp.arange(nchunks, dtype=jnp.int32))
    return s.replace(dirs=dirs_local,
                     need_replan=jnp.zeros_like(s.need_replan))


def _replan_2d(cfg: SolverConfig, s: MapdState, free_local: jnp.ndarray
               ) -> MapdState:
    """Drain stale field rows owned by this agent block; each tiles-axis
    device computes its band via the halo-exchanged tiled sweep.  Narrow
    steady-state chunk — the t=0 burst goes through _prime_2d."""
    n = cfg.num_agents
    dirs_local = s.dirs
    rows_local, words_local = dirs_local.shape
    a_shard = jax.lax.axis_index(AGENTS_AXIS)
    idx = jnp.arange(n, dtype=jnp.int32)
    r = min(cfg.replan_chunk_small, n)
    own = s.need_replan & (s.slot // rows_local == a_shard)

    # The loop body runs tiles-axis collectives (halo exchange + fixpoint
    # psum inside the tiled sweep), so every device MUST execute the same
    # number of rounds — a data-dependent `while any(own)` would give agent
    # blocks with fewer stale rows a shorter collective schedule and
    # deadlock the others.  pmax the per-block round count first; blocks
    # that finish early run no-op rounds (all-invalid lanes write only the
    # scratch row).
    rounds = (jnp.sum(own) + r - 1) // r
    rounds = jax.lax.pmax(rounds, AGENTS_AXIS)

    def body(_, carry):
        dirs_local, own = carry
        priority = jnp.where(own, idx, n)
        sel = -jax.lax.top_k(-priority, r)[0]
        valid = sel < n
        selc = jnp.clip(sel, 0, n - 1)
        fields = tiled_direction_fields(
            free_local, s.goal[selc], cfg.width, axis_name=TILES_AXIS,
            max_rounds=cfg.max_sweep_rounds,
            # uniform sweep schedule across the whole mesh (see _replan_2d's
            # rounds pmax): agent blocks sweep different goal batches, and
            # collectives must line up across them too
            fixpoint_axes=(AGENTS_AXIS, TILES_AXIS))
        fields = pack_directions(fields.reshape(r, -1))  # (r, words_local)
        local_row = jnp.where(valid, s.slot[selc] - a_shard * rows_local,
                              rows_local)
        padded = jnp.concatenate(
            [dirs_local, jnp.zeros((1, words_local), dirs_local.dtype)])
        dirs_local = padded.at[local_row].set(fields)[:rows_local]
        cleared = jnp.zeros(n, bool).at[selc].max(valid)
        return dirs_local, own & ~cleared

    dirs_local, _ = jax.lax.fori_loop(0, rounds, body, (dirs_local, own))
    return s.replace(dirs=dirs_local,
                     need_replan=jnp.zeros_like(s.need_replan))


def _nh_factory_2d(cfg: SolverConfig, dirs_local: jnp.ndarray):
    return functools.partial(_next_hops_2d, cfg, dirs_local)


def sharded2d_mapd_step(cfg: SolverConfig, s: MapdState, tasks: jnp.ndarray,
                        free_local: jnp.ndarray) -> MapdState:
    """One MAPD timestep inside the 2-D shard_map: single-device sequencing
    with the 2-D replan and next-hop lookup swapped in."""
    return mapd_mod.mapd_step(cfg, s, tasks, free_local,
                              replan_fn=_replan_2d,
                              nh_factory=_nh_factory_2d)


def state_specs_2d() -> MapdState:
    return MapdState(
        pos=P(), goal=P(), slot=P(),
        dirs=P(AGENTS_AXIS, TILES_AXIS), phase=P(),
        agent_task=P(), task_used=P(), need_replan=P(), t=P(),
        paths_pos=P(), paths_state=P(),
        vpos=P(), vgoal=P(), vstamp=P(), pend_from=P(), pend_push=P())


def make_sharded2d_runner(cfg: SolverConfig, mesh: Mesh):
    """Jitted end-to-end MAPD solve over a 2-D (agents x tiles) mesh.

    Returns ``run(starts (N,), tasks (T,2), free (H,W)) -> MapdState``.
    """
    n_agent_shards = mesh.shape[AGENTS_AXIS]
    n_tiles = mesh.shape[TILES_AXIS]
    assert cfg.num_agents % n_agent_shards == 0, (
        f"num_agents={cfg.num_agents} must divide over {n_agent_shards} "
        "agent shards")
    assert cfg.height % n_tiles == 0, (
        f"height={cfg.height} must divide over {n_tiles} tiles")
    band_cells = (cfg.height // n_tiles) * cfg.width
    assert band_cells % 8 == 0, (
        f"band cell count {band_cells} must be a multiple of 8 "
        "(whole packed words per band)")

    specs = state_specs_2d()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(specs, P(), P(TILES_AXIS, None)), out_specs=specs,
        check_vma=False)
    def run_shard(s, tasks, free_local):
        s = _prime_2d(cfg, s, free_local)  # wide t=0 burst, off the hot loop

        def cond(s):
            return ~mapd_mod._finished(cfg, s)

        def body(s):
            return sharded2d_mapd_step(cfg, s, tasks, free_local)

        return jax.lax.while_loop(cond, body, s)

    @jax.jit
    def run(starts, tasks, free):
        if tasks.shape[0] == 0:
            tasks = jnp.zeros((1, 2), jnp.int32)
            s = init_state(cfg, starts, 1)
            s = s.replace(task_used=jnp.ones(1, bool))
        else:
            s = init_state(cfg, starts, tasks.shape[0])
        # match mapd.prepare_state's pre-loop transitions + assignment so
        # sharded runs stay bit-identical to the single-device solver
        # (see parallel/sharded.py for the ordering rationale)
        s = mapd_mod._transitions(cfg, s, tasks)
        s = mapd_mod._assign(cfg, s, tasks)
        return run_shard(s, tasks, free)

    return run


def solve_offline_sharded2d(grid: Grid, starts_idx: np.ndarray,
                            tasks: np.ndarray,
                            cfg: SolverConfig | None = None,
                            mesh: Mesh | None = None,
                            n_agent_shards: int = 2, n_tiles: int = 4
                            ) -> Tuple[np.ndarray, np.ndarray, int]:
    """2-D sharded counterpart of mapd.solve_offline (same contract)."""
    if cfg is None:
        cfg = SolverConfig(height=grid.height, width=grid.width,
                           num_agents=len(starts_idx))
    if mesh is None:
        mesh = agent_tile_mesh(n_agent_shards, n_tiles)
    mapd_mod.validate_starts(grid, starts_idx)
    mapd_mod.validate_tasks(grid, tasks)
    run = make_sharded2d_runner(cfg, mesh)
    final = run(jnp.asarray(starts_idx, jnp.int32),
                jnp.asarray(tasks, jnp.int32), jnp.asarray(grid.free))
    makespan = int(final.t)
    if not cfg.record_paths:
        n = len(starts_idx)
        return (np.zeros((0, n), np.int32), np.zeros((0, n), np.int8),
                makespan)
    return (np.asarray(final.paths_pos[:makespan]),
            np.asarray(final.paths_state[:makespan]), makespan)
