"""Mesh-sharded solverd execution (ISSUE 13): the live planning plane
spans a device mesh.

Everything the solver daemon keeps device-resident — the direction-field
cache (the dominant buffer: O(cached goals x HW/2) bytes), the flat
fleet lanes, and the multi-tenant [T, L] super-batch — becomes sharded
arrays on a ``jax.sharding.Mesh``, and the step/sweep programs run under
``shard_map``:

- **field ROWS shard over the agents axis** (``parallel/sharded.py``'s
  proven layout): each device holds ``rows / A`` packed rows, so peak
  per-device HBM shrinks ~mesh-size.  The step's only cross-shard
  traffic is the next-hop exchange: the device owning ``slot[i]``'s row
  block contributes agent i's direction code and ONE ``psum`` assembles
  the replicated (N,) vector — bit-identical integer math, O(N) bytes
  per lookup.
- **lane state (pos/goal/slot/active) shards over the agents axis** in
  HBM and is re-replicated at step entry (control flow — occupancy,
  swap rules, the movement cascade — is replicated determinism, exactly
  the ``parallel/sharded.py`` contract).
- **optional grid-tile axis** (``AxT`` specs): the field sweeps run as
  H-banded local sweeps + one-row halo exchanges per round
  (``ops/tiled_distance.py``, bit-identical per its tests); the dirs
  cache itself stays row-sharded only (the tiles axis is a sweep
  throughput/workspace lever, not a cache-residency one).

The solverd paths that consume this module keep their exact wire and
host bookkeeping; sharding is purely an execution/residency lever.  The
exactness contract — mesh solverd produces bit-identical plans, packed
rows, and audit digests to the single-device daemon — is enforced by
tests/test_mesh_solverd.py on the virtual CPU mesh
(``parallel/virtual_mesh.py``).

``parse_mesh_spec`` grammar (JG_SOLVER_MESH / solverd --mesh):
``"4"`` = 4-way agent-axis mesh, ``"2x4"`` = 2 agent shards x 4 grid
tiles, ``"1"``/``"1x1"`` = explicit single-device (callers treat it as
mesh OFF — the flat path).
"""

from __future__ import annotations

import functools
import re
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2p_distributed_tswap_tpu.ops.distance import (
    apply_direction,
    direction_fields,
    directions_from_distance,
    distance_fields,
    gather_packed,
    pack_directions,
)
from p2p_distributed_tswap_tpu.ops.tiled_distance import (
    tiled_directions_from_distance,
    tiled_distance_fields,
)
from p2p_distributed_tswap_tpu.parallel.mesh import (AGENTS_AXIS,
    TILES_AXIS, shard_map)
from p2p_distributed_tswap_tpu.solver.step import step_with_next_hops

_SPEC_RE = re.compile(r"^(\d+)(?:x(\d+))?$")


def parse_mesh_spec(spec: str) -> Tuple[int, int]:
    """``"N"`` -> (N, 1); ``"AxT"`` -> (A, T).  Raises ValueError on
    anything else (zero counts included) — a malformed mesh spec must
    fail loudly at startup, never silently serve single-device."""
    m = _SPEC_RE.match(str(spec).strip().lower())
    if m is None:
        raise ValueError(f"bad mesh spec {spec!r} (want N or AxT)")
    a = int(m.group(1))
    t = int(m.group(2)) if m.group(2) is not None else 1
    if a < 1 or t < 1:
        raise ValueError(f"bad mesh spec {spec!r}: counts must be >= 1")
    return a, t


def mesh_spec_from_env(env: Optional[str]) -> Optional[Tuple[int, int]]:
    """JG_SOLVER_MESH value -> (A, T), with unset/empty/1/1x1 -> None
    (the single-device path)."""
    if not env:
        return None
    a, t = parse_mesh_spec(env)
    if a * t == 1:
        return None
    return a, t


def _default_devices(n: int):
    """First ``n`` devices of the default-device platform (a CPU-forced
    test session gets the virtual CPU mesh even with a TPU plugin
    registered) — same resolution rule as parallel.mesh."""
    default = jax.config.jax_default_device
    devices = (jax.devices(default.platform) if default is not None
               else jax.devices())
    if len(devices) < n:
        raise RuntimeError(
            f"mesh needs {n} devices, have {len(devices)} "
            f"(virtual CPU mesh: XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} before jax "
            f"creates its CPU client)")
    return devices[:n]


def _local_next_hops(cfg, dirs_local: jnp.ndarray):
    """The distributed ``dirs[slot[i], pos[i]]`` for solverd lanes: slot
    is NOT a permutation (many lanes may share a goal row, rows may be
    unreferenced), so ownership is by row-block — the shard holding
    ``slot[i] // rows_local`` contributes lane i's code, one psum
    assembles all N.  Exact: exactly one shard contributes a nonzero
    int32 per lane."""
    rows_local = dirs_local.shape[0]

    def nh(slot, pos):
        shard = jax.lax.axis_index(AGENTS_AXIS)
        local = (slot // rows_local) == shard
        lrow = jnp.where(local, slot - shard * rows_local, 0)
        vals = gather_packed(dirs_local, lrow, pos)
        contrib = jnp.where(local, vals.astype(jnp.int32), 0)
        codes = jax.lax.psum(contrib, AGENTS_AXIS).astype(jnp.uint8)
        return apply_direction(pos, codes, cfg.width)

    return nh


class SolverMesh:
    """One solverd process's device mesh + the sharded program builders.

    ``n_agent_shards`` (A) splits field rows / lanes; ``n_tiles`` (T)
    optionally bands the sweeps over grid rows.  The mesh is
    (A x T)-shaped even when T == 1 so axis names stay uniform."""

    def __init__(self, n_agent_shards: int, n_tiles: int = 1,
                 devices=None):
        if n_agent_shards < 1 or n_tiles < 1:
            raise ValueError("mesh axes must be >= 1")
        self.n_agent_shards = n_agent_shards
        self.n_tiles = n_tiles
        self.n_devices = n_agent_shards * n_tiles
        if devices is None:
            devices = _default_devices(self.n_devices)
        self.mesh = Mesh(
            np.array(devices[:self.n_devices]).reshape(n_agent_shards,
                                                       n_tiles),
            (AGENTS_AXIS, TILES_AXIS))
        self.row_sharding = NamedSharding(self.mesh, P(AGENTS_AXIS, None))
        self.lane_sharding = NamedSharding(self.mesh, P(AGENTS_AXIS))
        self.slab_sharding = NamedSharding(self.mesh,
                                           P(None, AGENTS_AXIS))
        self.replicated = NamedSharding(self.mesh, P())

    @property
    def shape_str(self) -> str:
        return f"{self.n_agent_shards}x{self.n_tiles}"

    # -- geometry helpers -------------------------------------------------
    def round_lanes(self, n: int) -> int:
        """Next multiple of the agent-shard count (lane capacities must
        divide over the shards; pow2 doubling preserves the property)."""
        a = self.n_agent_shards
        return -(-n // a) * a

    def round_rows(self, rows: int) -> int:
        return self.round_lanes(rows)

    def validate_grid(self, grid) -> None:
        if self.n_tiles > 1 and grid.height % self.n_tiles:
            raise ValueError(
                f"grid height {grid.height} must divide over "
                f"{self.n_tiles} tiles (mesh {self.shape_str})")

    # -- array placement --------------------------------------------------
    def pin_rows(self, arr):
        """Row-shard the (rows, words) dirs cache (rows % A == 0,
        enforced by the callers' round_rows growth)."""
        return jax.device_put(arr, self.row_sharding)

    def pin_lanes(self, arr):
        """Agent-axis-shard a per-lane vector (replicate when the length
        doesn't divide — correctness never depends on the layout)."""
        if arr.shape[0] % self.n_agent_shards:
            return jax.device_put(arr, self.replicated)
        return jax.device_put(arr, self.lane_sharding)

    def pin_slab(self, arr):
        """Lane-axis-shard a [T_cap, L_cap] slab plane."""
        if arr.shape[1] % self.n_agent_shards:
            return jax.device_put(arr, self.replicated)
        return jax.device_put(arr, self.slab_sharding)

    def shard_bytes(self, arrays) -> Dict[int, int]:
        """Per-device resident bytes of ``arrays`` (addressable shards
        only — exact on the virtual CPU mesh and on a single host's
        chips).  Keys are mesh positions 0..n_devices-1, stable across
        runs."""
        order = {d.id: k for k, d in
                 enumerate(self.mesh.devices.reshape(-1))}
        per: Dict[int, int] = {k: 0 for k in range(self.n_devices)}
        for a in arrays:
            if a is None:
                continue
            shards = getattr(a, "addressable_shards", None)
            if shards is None:
                continue
            for s in shards:
                k = order.get(s.device.id)
                if k is not None:
                    per[k] += int(np.prod(s.data.shape)) * s.data.dtype.itemsize
        return per

    # -- sharded programs -------------------------------------------------
    def make_step(self):
        """Jitted ``step(cfg, pos, goal, slot, dirs, active)`` matching
        solver.step.step_parallel's contract, executed under shard_map:
        dirs row-sharded, everything else replicated, the next-hop psum
        the only collective.  Bit-identical to the flat step."""
        mesh = self.mesh

        @functools.partial(jax.jit, static_argnums=0)
        def mesh_step(cfg, pos, goal, slot, dirs, active):
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(P(), P(), P(), P(AGENTS_AXIS, None), P()),
                out_specs=(P(), P(), P()), check_vma=False)
            def inner(pos, goal, slot, dirs_local, active):
                nh = _local_next_hops(cfg, dirs_local)
                return step_with_next_hops(cfg, pos, goal, slot, nh,
                                           active)

            return inner(pos, goal, slot, dirs, active)

        return mesh_step

    def make_slab_step(self, cfg):
        """The multi-tenant super-batch step under shard_map: one vmap
        over tenant rows INSIDE the mesh program (each row's next-hop
        lookups psum over the shared row-sharded field cache).  Same
        call signature as TenantSlab's flat vstep."""
        mesh = self.mesh

        @jax.jit
        def mesh_vstep(pos, goal, slot, active, dirs):
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(P(), P(), P(), P(), P(AGENTS_AXIS, None)),
                out_specs=(P(), P(), P()), check_vma=False)
            def inner(pos, goal, slot, active, dirs_local):
                def one(p, g, s, a):
                    nh = _local_next_hops(cfg, dirs_local)
                    return step_with_next_hops(cfg, p, g, s, nh, a)

                return jax.vmap(one)(pos, goal, slot, active)

            return inner(pos, goal, slot, active, dirs)

        return mesh_vstep

    def _pad_goals(self, goals: jnp.ndarray) -> jnp.ndarray:
        g = goals.shape[0]
        pad = -g % self.n_agent_shards
        if pad:
            goals = jnp.concatenate(
                [goals, jnp.broadcast_to(goals[-1:], (pad,))])
        return goals

    def make_fields(self, grid):
        """Sharded twin of PlanService._fields: goal batch split over
        the agents axis (per-goal sweeps are independent, so batching is
        bit-identical), each goal's sweep optionally H-banded over the
        tiles axis with halo exchanges (ops/tiled_distance — also
        bit-identical).  Returns a python wrapper that pads the goal
        batch to a shard multiple and slices the result back."""
        mesh, width = self.mesh, grid.width
        n_tiles = self.n_tiles

        if n_tiles == 1:
            @jax.jit
            def fields_sharded(free, goals):
                @functools.partial(
                    shard_map, mesh=mesh,
                    in_specs=(P(), P(AGENTS_AXIS)),
                    out_specs=P(AGENTS_AXIS, None), check_vma=False)
                def inner(free, goals_local):
                    d = direction_fields(free, goals_local)
                    return pack_directions(
                        d.reshape(goals_local.shape[0], -1))

                return inner(free, goals)
        else:
            @jax.jit
            def fields_sharded(free, goals):
                @functools.partial(
                    shard_map, mesh=mesh,
                    in_specs=(P(TILES_AXIS, None), P(AGENTS_AXIS)),
                    out_specs=P(AGENTS_AXIS, TILES_AXIS, None),
                    check_vma=False)
                def inner(free_local, goals_local):
                    # uniform collective schedule across agent blocks:
                    # they sweep different goal batches, so the halo /
                    # fixpoint collectives must line up mesh-wide
                    d = tiled_distance_fields(
                        free_local, goals_local, width,
                        axis_name=TILES_AXIS,
                        fixpoint_axes=(AGENTS_AXIS, TILES_AXIS))
                    return tiled_directions_from_distance(
                        d, free_local, axis_name=TILES_AXIS)

                codes = inner(free, goals)          # (G, H, W) global
                return pack_directions(
                    codes.reshape(goals.shape[0], -1))

        def wrapper(free, goals):
            g = goals.shape[0]
            return fields_sharded(free, self._pad_goals(goals))[:g]

        return wrapper

    def make_fields_dist(self, grid):
        """Sharded twin of PlanService._fields_dist (dynamic-world
        variant): packed rows plus the raw distance/direction fields the
        host repair mirrors start from."""
        mesh, width = self.mesh, grid.width
        n_tiles = self.n_tiles

        if n_tiles == 1:
            @jax.jit
            def fd_sharded(free, goals):
                @functools.partial(
                    shard_map, mesh=mesh,
                    in_specs=(P(), P(AGENTS_AXIS)),
                    out_specs=(P(AGENTS_AXIS, None),
                               P(AGENTS_AXIS, None, None),
                               P(AGENTS_AXIS, None, None)),
                    check_vma=False)
                def inner(free, goals_local):
                    d = distance_fields(free, goals_local)
                    dirs = directions_from_distance(d, free)
                    return (pack_directions(
                        dirs.reshape(goals_local.shape[0], -1)), d, dirs)

                return inner(free, goals)
        else:
            @jax.jit
            def fd_sharded(free, goals):
                @functools.partial(
                    shard_map, mesh=mesh,
                    in_specs=(P(TILES_AXIS, None), P(AGENTS_AXIS)),
                    out_specs=(P(AGENTS_AXIS, TILES_AXIS, None),
                               P(AGENTS_AXIS, TILES_AXIS, None)),
                    check_vma=False)
                def inner(free_local, goals_local):
                    d = tiled_distance_fields(
                        free_local, goals_local, width,
                        axis_name=TILES_AXIS,
                        fixpoint_axes=(AGENTS_AXIS, TILES_AXIS))
                    codes = tiled_directions_from_distance(
                        d, free_local, axis_name=TILES_AXIS)
                    return d, codes

                d, dirs = inner(free, goals)        # global (G, H, W)
                return (pack_directions(
                    dirs.reshape(goals.shape[0], -1)), d, dirs)

        def wrapper(free, goals):
            g = goals.shape[0]
            packed, d, dirs = fd_sharded(free, self._pad_goals(goals))
            return packed[:g], d[:g], dirs[:g]

        return wrapper
