"""Metrics subsystem: task lifecycle, path computation, network counters.

Behavior and CSV-schema parity with the reference's strongest subsystem
(src/map/task_metrics.rs, SURVEY C11):

- ``TaskMetric``: sent -> received -> started -> completed lifecycle with
  Unix-ms timestamps and derived total / processing / startup-latency times
  (task_metrics.rs:6-62).
- ``TaskMetricsCollector``: add/update/statistics and the exact CSV header
  ``task_id,peer_id,sent_time_ms,received_time_ms,start_time_ms,
  completion_time_ms,total_time_ms,processing_time_ms,startup_latency_ms,
  status`` (task_metrics.rs:179-182) — the reference's offline analysis
  scripts (analyze_metrics.py) consume our CSVs unchanged.
- ``PathComputationMetrics``: microsecond samples with
  ``sample_index,duration_micros,duration_millis`` CSV (task_metrics.rs:332-339),
  consumed unchanged by compare_path_metrics.py.
- ``NetworkMetrics``: message/byte counters with rate and kbps derivations
  (task_metrics.rs:382-476).

The C++ host runtime (cpp/) writes the same schemas natively; this module is
the Python-side implementation for the solver daemon and offline harnesses,
and the executable schema contract the tests pin down.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, List, Optional


def now_ms() -> int:
    return int(time.time() * 1000)


class TaskStatus(enum.Enum):
    PENDING = "pending"
    SENT = "sent"
    RECEIVED = "received"
    RUNNING = "running"
    COMPLETED = "completed"
    FAILED = "failed"


@dataclasses.dataclass
class TaskMetric:
    task_id: int
    peer_id: str
    sent_time: int = dataclasses.field(default_factory=now_ms)
    received_time: Optional[int] = None
    start_time: Optional[int] = None
    completion_time: Optional[int] = None
    status: TaskStatus = TaskStatus.SENT

    # Derivations subtract timestamps STAMPED BY DIFFERENT PEERS (sent by
    # the manager, started/completed by the agent, each on its own wall
    # clock) and are clamped to >= 0: peer clock skew beyond the message
    # latency otherwise yields negative latencies that poison averages and
    # flip CSV consumers' sorts.  Skew occurrences are counted by the
    # collector (clock_skew_events) at update time so the clamp is never
    # silent.
    def get_total_time(self) -> Optional[int]:
        if self.completion_time is None:
            return None
        return max(0, self.completion_time - self.sent_time)

    def get_agent_processing_time(self) -> Optional[int]:
        if self.start_time is None or self.completion_time is None:
            return None
        return max(0, self.completion_time - self.start_time)

    def get_startup_latency(self) -> Optional[int]:
        if self.start_time is None:
            return None
        return max(0, self.start_time - self.sent_time)


@dataclasses.dataclass
class TaskStatistics:
    total_tasks: int
    completed_tasks: int
    failed_tasks: int
    avg_total_time: int
    avg_processing_time: int
    avg_startup_latency: int
    min_total_time: int
    max_total_time: int
    min_processing_time: int
    max_processing_time: int

    def __str__(self) -> str:  # display parity: task_metrics.rs:246-273
        rate = (100.0 * self.completed_tasks / self.total_tasks
                if self.total_tasks else 0.0)
        return (
            "\U0001F4CA Task Statistics:\n"
            f"├─ Total Tasks: {self.total_tasks}\n"
            f"├─ Completed: {self.completed_tasks} "
            f"(Success Rate: {rate:.1f}%)\n"
            f"├─ Failed: {self.failed_tasks}\n"
            f"├─ Avg Total Time: {self.avg_total_time} ms\n"
            f"├─ Avg Processing Time: {self.avg_processing_time} ms\n"
            f"├─ Avg Startup Latency: {self.avg_startup_latency} ms\n"
            f"├─ Min/Max Total Time: {self.min_total_time} ms / "
            f"{self.max_total_time} ms\n"
            f"└─ Min/Max Processing Time: {self.min_processing_time}"
            f" ms / {self.max_processing_time} ms")


class TaskMetricsCollector:
    """Task-metric sink (task_metrics.rs:65-227)."""

    CSV_HEADER = ("task_id,peer_id,sent_time_ms,received_time_ms,"
                  "start_time_ms,completion_time_ms,total_time_ms,"
                  "processing_time_ms,startup_latency_ms,status")

    def __init__(self):
        self.metrics: Dict[int, TaskMetric] = {}
        # NetworkMetrics-style counters: how often a peer-stamped timestamp
        # landed BEFORE its predecessor (wall clocks disagree); the
        # TaskMetric derivations clamp, these keep the evidence
        self.clock_skew_events = 0
        self.clock_skew_worst_ms = 0

    def _note_skew(self, earlier: Optional[int], later: int) -> None:
        if earlier is not None and later < earlier:
            self.clock_skew_events += 1
            self.clock_skew_worst_ms = max(self.clock_skew_worst_ms,
                                           earlier - later)

    def add_metric(self, metric: TaskMetric) -> None:
        self.metrics[metric.task_id] = metric

    def update_received(self, task_id: int, at_ms: Optional[int] = None) -> None:
        m = self.metrics.get(task_id)
        if m is not None:
            m.received_time = now_ms() if at_ms is None else at_ms
            self._note_skew(m.sent_time, m.received_time)
            m.status = TaskStatus.RECEIVED

    def update_started(self, task_id: int, at_ms: Optional[int] = None) -> None:
        m = self.metrics.get(task_id)
        if m is not None:
            m.start_time = now_ms() if at_ms is None else at_ms
            self._note_skew(m.sent_time, m.start_time)
            m.status = TaskStatus.RUNNING

    def update_completed(self, task_id: int, at_ms: Optional[int] = None) -> None:
        m = self.metrics.get(task_id)
        if m is not None:
            m.completion_time = now_ms() if at_ms is None else at_ms
            self._note_skew(m.start_time if m.start_time is not None
                            else m.sent_time, m.completion_time)
            m.status = TaskStatus.COMPLETED

    def update_failed(self, task_id: int) -> None:
        m = self.metrics.get(task_id)
        if m is not None:
            m.status = TaskStatus.FAILED

    def get_statistics(self) -> TaskStatistics:
        completed = [m for m in self.metrics.values()
                     if m.status == TaskStatus.COMPLETED]
        totals = [t for t in (m.get_total_time() for m in completed)
                  if t is not None]
        procs = [t for t in (m.get_agent_processing_time() for m in completed)
                 if t is not None]
        starts = [t for t in (m.get_startup_latency() for m in completed)
                  if t is not None]
        # integer division like the reference (u64 sums / len)
        return TaskStatistics(
            total_tasks=len(self.metrics),
            completed_tasks=len(completed),
            failed_tasks=sum(1 for m in self.metrics.values()
                             if m.status == TaskStatus.FAILED),
            avg_total_time=sum(totals) // len(totals) if totals else 0,
            avg_processing_time=sum(procs) // len(procs) if procs else 0,
            avg_startup_latency=sum(starts) // len(starts) if starts else 0,
            min_total_time=min(totals, default=0),
            max_total_time=max(totals, default=0),
            min_processing_time=min(procs, default=0),
            max_processing_time=max(procs, default=0))

    def to_csv_string(self) -> str:
        """Exact schema of task_metrics.rs:179-227: missing timestamps render
        as 0, missing derived times as empty strings."""
        lines = [self.CSV_HEADER]
        for m in sorted(self.metrics.values(), key=lambda m: m.task_id):
            def opt(v):
                return "" if v is None else str(v)
            lines.append(
                f"{m.task_id},{m.peer_id},{m.sent_time},"
                f"{m.received_time or 0},{m.start_time or 0},"
                f"{m.completion_time or 0},{opt(m.get_total_time())},"
                f"{opt(m.get_agent_processing_time())},"
                f"{opt(m.get_startup_latency())},{m.status.value}")
        return "\n".join(lines) + "\n"


@dataclasses.dataclass
class PathComputationStatistics:
    samples: int
    avg_micros: float
    min_micros: int
    max_micros: int

    def avg_millis(self) -> float:
        return self.avg_micros / 1000.0

    def min_millis(self) -> float:
        return self.min_micros / 1000.0

    def max_millis(self) -> float:
        return self.max_micros / 1000.0

    def __str__(self) -> str:
        return ("⏱️ Path Computation Stats:\n"
                f"├─ Samples: {self.samples}\n"
                f"├─ Avg: {self.avg_millis():.3f} ms\n"
                f"├─ Min: {self.min_millis():.3f} ms\n"
                f"└─ Max: {self.max_millis():.3f} ms")


class PathComputationMetrics:
    """Per-decision / per-planning-step wall-clock samples in microseconds
    (task_metrics.rs:277-340)."""

    def __init__(self):
        self.samples: List[int] = []
        self.timestamps_ms: List[Optional[int]] = []

    def clear(self) -> None:
        self.samples.clear()
        self.timestamps_ms.clear()

    def record_duration(self, seconds: float,
                        timestamp_ms: Optional[int] = None) -> None:
        self.record_micros(int(seconds * 1e6), timestamp_ms)

    def record_micros(self, micros: int,
                      timestamp_ms: Optional[int] = None) -> None:
        """``timestamp_ms`` is the optional wall-clock stamp the decentralized
        wire protocol carries in path_metric messages
        (src/bin/decentralized/agent.rs:302-308); compare_path_metrics.py
        groups decentralized samples into 100 ms buckets by it (:48-52)."""
        self.samples.append(int(micros))
        self.timestamps_ms.append(timestamp_ms)

    def __len__(self) -> int:
        return len(self.samples)

    def is_empty(self) -> bool:
        return not self.samples

    def get_statistics(self) -> Optional[PathComputationStatistics]:
        if not self.samples:
            return None
        s = sorted(self.samples)
        return PathComputationStatistics(
            samples=len(s), avg_micros=sum(s) / len(s),
            min_micros=s[0], max_micros=s[-1])

    def to_csv_string(self) -> str:
        """Reference schema (task_metrics.rs:332-339); when wall-clock stamps
        were recorded a trailing ``timestamp_ms`` column is appended (used by
        compare_path_metrics.py's per-step bucketing)."""
        with_ts = any(t is not None for t in self.timestamps_ms)
        header = "sample_index,duration_micros,duration_millis"
        lines = [header + ",timestamp_ms" if with_ts else header]
        for i, us in enumerate(self.samples):
            row = f"{i},{us},{us / 1000.0:.3f}"
            if with_ts:
                ts = self.timestamps_ms[i]
                # unstamped samples render empty (pandas NaN, dropped by the
                # bucketing groupby) rather than as epoch-0 rows
                row += f",{'' if ts is None else ts}"
            lines.append(row)
        return "\n".join(lines) + "\n"


class NetworkMetrics:
    """Message/byte counters with rates (task_metrics.rs:382-476)."""

    def __init__(self):
        self.messages_sent = 0
        self.messages_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self._start = time.monotonic()

    def record_sent(self, nbytes: int) -> None:
        self.messages_sent += 1
        self.bytes_sent += nbytes

    def record_received(self, nbytes: int) -> None:
        self.messages_received += 1
        self.bytes_received += nbytes

    def get_elapsed_secs(self) -> float:
        return time.monotonic() - self._start

    def get_send_rate(self) -> float:
        e = self.get_elapsed_secs()
        return self.messages_sent / e if e > 0 else 0.0

    def get_recv_rate(self) -> float:
        e = self.get_elapsed_secs()
        return self.messages_received / e if e > 0 else 0.0

    def get_bandwidth_sent_kbps(self) -> float:
        e = self.get_elapsed_secs()
        return (self.bytes_sent * 8.0) / (e * 1000.0) if e > 0 else 0.0

    def get_bandwidth_recv_kbps(self) -> float:
        e = self.get_elapsed_secs()
        return (self.bytes_received * 8.0) / (e * 1000.0) if e > 0 else 0.0

    def __str__(self) -> str:
        return (
            "\U0001F4E1 Network Communication Stats:\n"
            f"├─ Messages sent: {self.messages_sent} "
            f"({self.get_send_rate():.1f} msg/s)\n"
            f"├─ Messages received: {self.messages_received} "
            f"({self.get_recv_rate():.1f} msg/s)\n"
            f"├─ Bandwidth sent: {self.bytes_sent / 1024.0:.2f} KB "
            f"({self.get_bandwidth_sent_kbps():.1f} kbps)\n"
            f"├─ Bandwidth received: "
            f"{self.bytes_received / 1024.0:.2f} KB "
            f"({self.get_bandwidth_recv_kbps():.1f} kbps)\n"
            f"└─ Duration: {self.get_elapsed_secs():.1f}s")
