from p2p_distributed_tswap_tpu.core.grid import Grid, DEFAULT_MAP_ASCII
from p2p_distributed_tswap_tpu.core.tasks import Task, TaskGenerator
from p2p_distributed_tswap_tpu.core.agent import AgentPhase, AgentState
from p2p_distributed_tswap_tpu.core.config import SolverConfig

__all__ = [
    "Grid",
    "DEFAULT_MAP_ASCII",
    "Task",
    "TaskGenerator",
    "AgentPhase",
    "AgentState",
    "SolverConfig",
]
