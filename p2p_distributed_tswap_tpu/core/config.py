"""Configuration system.

The reference scatters its knobs across compile-time constants, one CLI flag and
two env vars (SURVEY §5: TSWAP_RADIUS=15 at src/bin/decentralized/agent.rs:796,
planning interval 500 ms at src/bin/centralized/manager.rs:567, timestep cap
2000 at src/algorithm/tswap.rs:167, memory caps, gossipsub tunings, --clean,
TASK_CSV_PATH/PATH_CSV_PATH).  Here every knob lives in explicit frozen
dataclasses: ``SolverConfig`` is hashable and passed as a static jit argument
(shapes and loop bounds must be compile-time constants under XLA), and
``RuntimeConfig`` carries the host-runtime knobs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


def stale_knobs_active(visibility_radius, view_refresh_steps,
                       view_ttl_steps, swap_commit_delay) -> bool:
    """THE definition of "stale decentralized semantics engaged" — shared
    by SolverConfig.stale_mode (kernel selection) and the scenario/bench
    mode labels so the two can never disagree."""
    return visibility_radius is not None and (
        view_refresh_steps > 1 or swap_commit_delay > 0
        or view_ttl_steps is not None)


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Static (compile-time) solver parameters.

    Hashable so it can be a `static_argnums` jit argument; every field changes
    the compiled program (shapes or loop bounds).
    """

    height: int
    width: int
    num_agents: int
    # Offline-solver horizon cap (ref src/algorithm/tswap.rs:167).
    max_timesteps: int = 2000
    # Max direction-field recomputations processed per replan round; rounds
    # repeat until the dirty set drains. Static so replan has fixed shapes.
    replan_chunk: int = 64
    # Narrow chunk for the in-step replan loop — steady state dirties only
    # a handful of fields per step (task arrivals), and sweep cost is
    # O(chunk * H * W) per round regardless of how few rows are dirty.
    # Tuned on the FLAGSHIP rung: 4 -> 152 ms/step, 8 -> 206, 12 -> 328
    # (extra rounds at narrower chunks are cheaper than wasted sweep width).
    replan_chunk_small: int = 4
    # Rule-4 deadlock cycles are detected exactly up to this length
    # (ref walks unbounded chains, src/algorithm/tswap.rs:204-249; cycles
    # longer than this simply wait and retry next step).
    cycle_cap: int = 32
    # Decentralized-mode visibility radius (Manhattan); None = centralized
    # global view. Ref: TSWAP_RADIUS=15, src/bin/decentralized/agent.rs:796-801.
    visibility_radius: Optional[int] = None
    # --- stale/async decentralized semantics (ref agent.rs:156-167,
    # 730-789, 1041-1087) ----------------------------------------------
    # Neighbor-view refresh period in steps (the 500 ms position-broadcast
    # cadence analog): agent i re-publishes its (pos, goal) into the shared
    # view every ``view_refresh_steps`` steps on a per-agent phase offset
    # (i mod K), so cadences are decoupled like the reference's
    # per-process timers.  1 = every step (fresh views).
    view_refresh_steps: int = 1
    # View age-out in steps (the 10 s neighbor TTL analog, ref
    # agent.rs:156-167): view entries older than this are invisible
    # (their agent effectively absent).  None = no expiry.
    view_ttl_steps: Optional[int] = None
    # Goal-swap / rotation commit latency in steps: 1 = decisions taken at
    # step t commit at the START of step t+1 — the non-atomic wire
    # coordination analog (ref agent.rs:1041-1087: both sides mutate goals
    # at message-receipt time, not decision time); 0 = atomic in-step.
    # Only {0, 1} are meaningful (the pending buffer holds ONE step of
    # in-flight exchanges); validated in __post_init__.
    swap_commit_delay: int = 0

    def __post_init__(self):
        if self.swap_commit_delay not in (0, 1):
            raise ValueError(
                f"swap_commit_delay={self.swap_commit_delay}: only 0 "
                "(atomic) or 1 (one-step wire latency) are supported")
        # Probe the knob clause of THE shared predicate with a dummy
        # radius: true means "some stale knob is non-default", which is
        # invalid without a real radius.
        if self.visibility_radius is None and stale_knobs_active(
                0, self.view_refresh_steps, self.view_ttl_steps,
                self.swap_commit_delay):
            raise ValueError(
                "stale knobs (view_refresh_steps/view_ttl_steps/"
                "swap_commit_delay) require visibility_radius: staleness is "
                "a property of the neighbor view, and without a radius the "
                "centralized fresh-atomic kernel would silently run instead")
    # Rounds of the (Rule 3, Rule 4) goal-swapping phase per step.  The
    # reference's sequential pass lets swaps cascade within one step
    # (src/algorithm/tswap.rs:180-252); extra parallel rounds approximate that.
    swap_rounds: int = 2
    # Upper bound on movement-phase cascade rounds (each round finalizes at
    # least the front of every convoy; loop exits early at fixpoint).
    max_move_rounds: int = 64
    # Fast-sweeping rounds cap for distance fields (each round = 4 directional
    # scans; fixpoint is reached much earlier on benchmark maps).
    max_sweep_rounds: int = 128
    # Record per-step (pos, state) paths (ref tswap.rs:143-158).  Costs
    # (max_timesteps+1, N) x 5 bytes of device memory — disable for pure
    # benchmark/throughput runs (VERDICT r1 weak item 3).
    record_paths: bool = True
    # Task-chunk width for the parallel assignment's nearest-unused-task
    # search: transient memory is (num_agents, assign_chunk) int32 per chunk.
    assign_chunk: int = 1024

    @property
    def num_cells(self) -> int:
        return self.height * self.width

    @property
    def stale_mode(self) -> bool:
        """True when the decentralized kernel must model stale views and/or
        asynchronous coordination (the reference's actual decentralized
        reality) instead of the fresh-atomic radius mask.  Requires a
        visibility radius: staleness is a property of the neighbor view,
        and the centralized solver has no view — it has the truth."""
        return stale_knobs_active(self.visibility_radius,
                                  self.view_refresh_steps,
                                  self.view_ttl_steps,
                                  self.swap_commit_delay)


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Host-runtime knobs (C++ bus / manager / agents).

    Every field maps to a ``MAPD_*`` env var (and a ``--kebab-case`` CLI
    flag) read by the C++ binaries via cpp/common/knobs.hpp; precedence is
    flag > env > reference-parity default.  ``runtime.fleet.Fleet`` accepts a
    RuntimeConfig and exports it through :meth:`to_env`, so one dataclass
    configures a whole fleet end-to-end (SURVEY §5 "real config system").
    """

    # Centralized planning tick (ref 500 ms, src/bin/centralized/manager.rs:567).
    planning_interval_ms: int = 500
    # Decentralized per-agent decision cadence (ref src/bin/decentralized/agent.rs:730).
    decision_interval_ms: int = 500
    # Periodic state cleanup (ref 30 s, src/bin/centralized/manager.rs:727).
    cleanup_interval_ms: int = 30_000
    # Memory caps (ref manager.rs:734,752; decentralized/manager.rs:173;
    # decentralized/agent.rs:800-804).  The two peer caps are distinct
    # knobs because they cap different things at different reference
    # defaults: max_known_peers bounds the centralized manager's
    # departed-peer memory (ref 1000), max_tracked_peers bounds the
    # decentralized manager's subscribed-peer set (ref 200).
    max_tracked_agents: int = 500
    max_known_peers: int = 1000
    max_tracked_peers: int = 200
    max_cached_positions: int = 60
    max_cached_requests: int = 50
    # Neighbor-info age-out (ref 10 s, src/bin/decentralized/agent.rs:156-167).
    neighbor_ttl_ms: int = 10_000
    # Decentralized visibility radius (ref TSWAP_RADIUS=15,
    # src/bin/decentralized/agent.rs:796-801).
    visibility_radius: int = 15
    # Pending goal-swap / rotation retry window (our coordination layer).
    swap_timeout_ms: int = 2_000
    # Centralized agent position heartbeat (ref >=1 s, centralized/agent.rs:285-291).
    heartbeat_ms: int = 1_000
    # Managers treat agents/peers unseen for this long as dead: tracking
    # dropped and (beyond the reference) in-flight tasks re-queued.
    agent_stale_ms: int = 60_000
    # Centralized --solver=tpu: plan natively while the solver daemon has
    # produced no fresh response for this long (fleet must not stall).
    solver_failover_ms: int = 5_000
    # Agents retransmit `done` on this cadence until the manager's done_ack
    # arrives: a done published into a bus outage is dropped, which would
    # otherwise strand the manager's busy bookkeeping forever (the
    # reference simply loses such tasks, decentralized/manager.rs:185-189).
    done_retry_ms: int = 2_000
    # Managers re-send an in-flight task when its agent keeps reporting
    # idle past this grace (the Task publish was dropped in a bus outage).
    task_resend_ms: int = 5_000
    # Bus endpoint.
    bus_host: str = "127.0.0.1"
    bus_port: int = 7400
    topic: str = "mapd"
    # C++ binaries' log verbosity: error | warn | info | debug
    # (cpp/common/log.hpp; per-decision chatter sits at debug).
    log_level: str = "info"
    # CSV auto-save on exit (ref env vars TASK_CSV_PATH / PATH_CSV_PATH,
    # src/bin/decentralized/manager.rs:48-50).
    task_csv_path: Optional[str] = None
    path_csv_path: Optional[str] = None

    def to_env(self) -> dict:
        """Env-var map consumed by the C++ binaries (cpp/common/knobs.hpp).

        ``bus_port`` is omitted: the fleet passes it per-process as --port
        (each test fleet picks its own free port).  ``topic`` is likewise a
        wire-level constant ("mapd") shared with the reference protocol.
        """
        env = {
            "MAPD_BUS_HOST": self.bus_host,
            "MAPD_PLANNING_INTERVAL_MS": self.planning_interval_ms,
            "MAPD_DECISION_INTERVAL_MS": self.decision_interval_ms,
            "MAPD_CLEANUP_INTERVAL_MS": self.cleanup_interval_ms,
            "MAPD_MAX_TRACKED_AGENTS": self.max_tracked_agents,
            "MAPD_MAX_KNOWN_PEERS": self.max_known_peers,
            "MAPD_MAX_TRACKED_PEERS": self.max_tracked_peers,
            "MAPD_MAX_CACHED_POSITIONS": self.max_cached_positions,
            "MAPD_MAX_CACHED_REQUESTS": self.max_cached_requests,
            "MAPD_NEIGHBOR_TTL_MS": self.neighbor_ttl_ms,
            "MAPD_VISIBILITY_RADIUS": self.visibility_radius,
            "MAPD_SWAP_TIMEOUT_MS": self.swap_timeout_ms,
            "MAPD_HEARTBEAT_MS": self.heartbeat_ms,
            "MAPD_AGENT_STALE_MS": self.agent_stale_ms,
            "MAPD_SOLVER_FAILOVER_MS": self.solver_failover_ms,
            "MAPD_DONE_RETRY_MS": self.done_retry_ms,
            "MAPD_TASK_RESEND_MS": self.task_resend_ms,
            "MAPD_LOG_LEVEL": self.log_level,
        }
        if self.task_csv_path:
            env["TASK_CSV_PATH"] = self.task_csv_path
        if self.path_csv_path:
            env["PATH_CSV_PATH"] = self.path_csv_path
        return {k: str(v) for k, v in env.items()}
