"""Seeded sampling of start positions and start/goal pairs.

Capability parity with src/map/make_node.rs:
- ``get_free_cells``      -> Grid.free_cells (core/grid.py)
- ``generate_start_goal_pair(s)`` (:17-43)  -> sample_start_goal_pairs
- ``generate_start_positions``    (:45-49)  -> sample_start_positions

All sampling is deterministic given a seed (the reference's thread_rng is not),
and collision-free by construction — this also replaces the reference's racy
distributed initial-position protocol (src/bin/decentralized/agent.rs:518-650)
with deterministic collision-free assignment, per SURVEY §3.4.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from p2p_distributed_tswap_tpu.core.grid import Grid, Point


def sample_start_positions(grid: Grid, count: int, seed: int) -> List[Point]:
    """``count`` distinct random free cells (ref make_node.rs:45-49)."""
    free = grid.free_cells()
    assert count <= len(free), f"{count} agents > {len(free)} free cells"
    rng = np.random.default_rng(seed)
    pick = rng.choice(len(free), size=count, replace=False)
    return [(int(free[i][0]), int(free[i][1])) for i in pick]


def sample_start_goal_pairs(grid: Grid, count: int, seed: int) -> List[Tuple[Point, Point]]:
    """``count`` (start, goal) pairs over distinct free cells
    (ref make_node.rs:17-31: shuffle free cells, take disjoint pairs)."""
    free = grid.free_cells()
    assert 2 * count <= len(free), "not enough free cells for disjoint pairs"
    rng = np.random.default_rng(seed)
    pick = rng.choice(len(free), size=2 * count, replace=False)

    def pt(k: int) -> Point:
        return (int(free[k][0]), int(free[k][1]))

    return [(pt(pick[2 * i]), pt(pick[2 * i + 1])) for i in range(count)]


def start_positions_array(grid: Grid, count: int, seed: int) -> np.ndarray:
    """(count,) int32 flat indices of distinct random free cells."""
    pts = sample_start_positions(grid, count, seed)
    return np.array([grid.idx(p) for p in pts], dtype=np.int32)
