"""Dense grid maps.

The reference keeps its world as a 100x100 all-free ASCII constant
(``src/map/map.rs:1-106``: ``'.'`` = free, ``'@'`` = obstacle, ``Point=(x,y)``)
re-parsed by every binary.  Here the grid is a single dense ``(H, W)`` bool array
(True = free) — the layout XLA wants — with loaders for ASCII constants, MAPF
benchmark ``.map`` files, and procedural obstacle/warehouse generators for the
benchmark ladder (256^2 random-obstacle, 1024^2 warehouse, 4096^2).

Coordinates: ``Point = (x, y)`` tuples at the API edge (reference parity,
``src/map/map.rs:4``); internally everything is a flat row-major cell index
``idx = y * W + x`` (int32) so occupancy and field lookups are single gathers.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np

Point = Tuple[int, int]

# Reference parity: 100x100, all free (src/map/map.rs:5-105).
DEFAULT_WIDTH = 100
DEFAULT_HEIGHT = 100
DEFAULT_MAP_ASCII = "\n".join(["." * DEFAULT_WIDTH] * DEFAULT_HEIGHT)


@dataclasses.dataclass(frozen=True)
class Grid:
    """A static grid world. ``free`` is (H, W) bool, True where traversable."""

    free: np.ndarray  # (H, W) bool

    def __post_init__(self):
        assert self.free.ndim == 2 and self.free.dtype == np.bool_

    # -- constructors -------------------------------------------------------

    @staticmethod
    def default() -> "Grid":
        """The reference's built-in 100x100 empty map (src/map/map.rs:5)."""
        return Grid.from_ascii(DEFAULT_MAP_ASCII)

    @staticmethod
    def from_ascii(text: str) -> "Grid":
        """Parse '.'/'@' rows (same convention as the reference parse_map,
        e.g. src/bin/centralized/manager.rs:25-34). Blank lines are skipped."""
        rows = [line for line in text.splitlines() if line.strip()]
        w = len(rows[0])
        assert all(len(r) == w for r in rows), "ragged map rows"
        free = np.array([[c != "@" for c in row] for row in rows], dtype=np.bool_)
        return Grid(free)

    @staticmethod
    def from_mapf_file(path: str) -> "Grid":
        """Load a MAPF-benchmark ``.map`` file (movingai format: header of
        ``type/height/width/map`` then rows where ``.G S`` are free and
        ``@OTW`` are blocked)."""
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        assert lines[0].startswith("type"), f"not a movingai .map file: {path}"
        h = int(lines[1].split()[1])
        w = int(lines[2].split()[1])
        rows = lines[4 : 4 + h]
        free = np.zeros((h, w), dtype=np.bool_)
        for y, row in enumerate(rows):
            for x, c in enumerate(row[:w]):
                free[y, x] = c in ".GS"
        return Grid(free)

    @staticmethod
    def random_obstacles(height: int, width: int, density: float, seed: int) -> "Grid":
        """Random-obstacle grid (benchmark config "256x256 random-obstacle").

        Keeps only the largest connected free component so every free cell is
        mutually reachable (the solvers assume a connected free graph)."""
        rng = np.random.default_rng(seed)
        free = rng.random((height, width)) >= density
        free = _largest_component(free)
        return Grid(free)

    @staticmethod
    def warehouse(height: int, width: int, shelf_h: int = 2, shelf_w: int = 8,
                  aisle: int = 2, margin: int = 4) -> "Grid":
        """Procedural warehouse map: aligned shelf blocks separated by aisles —
        the structure of the MAPF warehouse benchmarks (1024^2 flagship config)."""
        free = np.ones((height, width), dtype=np.bool_)
        y = margin
        while y + shelf_h <= height - margin:
            x = margin
            while x + shelf_w <= width - margin:
                free[y : y + shelf_h, x : x + shelf_w] = False
                x += shelf_w + aisle
            y += shelf_h + aisle
        return Grid(free)

    # -- geometry -----------------------------------------------------------

    @property
    def height(self) -> int:
        return self.free.shape[0]

    @property
    def width(self) -> int:
        return self.free.shape[1]

    @property
    def num_cells(self) -> int:
        return self.free.size

    def free_cells(self) -> np.ndarray:
        """All free cells as (K, 2) array of (x, y) — enumeration order matches
        the reference's row-major scan (src/map/make_node.rs:5-15)."""
        ys, xs = np.nonzero(self.free)
        return np.stack([xs, ys], axis=1)

    def idx(self, p: Point) -> int:
        """Flat row-major index of point (x, y)."""
        x, y = p
        return int(y) * self.width + int(x)

    def point(self, idx: int) -> Point:
        return (int(idx) % self.width, int(idx) // self.width)

    def idx_array(self, points: np.ndarray) -> np.ndarray:
        """(K, 2) array of (x, y) -> (K,) flat indices."""
        return (points[:, 1].astype(np.int64) * self.width + points[:, 0]).astype(np.int32)

    def to_ascii(self) -> str:
        return "\n".join(
            "".join("." if c else "@" for c in row) for row in self.free
        )


def _largest_component(free: np.ndarray) -> np.ndarray:
    """Keep the largest 4-connected free component (two-pass C labeling;
    a per-cell Python flood fill would take minutes at the 4096^2 scale the
    benchmark ladder targets)."""
    if not free.any():
        return free
    from scipy import ndimage

    four_conn = np.array([[0, 1, 0], [1, 1, 1], [0, 1, 0]], dtype=bool)
    labels, n = ndimage.label(free, structure=four_conn)
    if n <= 1:
        return free
    counts = np.bincount(labels.reshape(-1))
    counts[0] = 0  # background
    return labels == int(np.argmax(counts))
