"""Agent state enums.

Mirrors the reference's two state vocabularies:
- path-recording states PICKING/CARRYING/DELIVERED/IDLE (src/map/agent.rs:9-15)
- the task-lifecycle machine Idle -> MovingToPickup -> MovingToDelivery used by
  both the offline solver (src/algorithm/tswap.rs:83-88) and the decentralized
  agent (src/bin/decentralized/agent.rs:81-88).

Values are small ints so they live in int8/int32 device arrays.
"""

from __future__ import annotations

import enum


class AgentPhase(enum.IntEnum):
    """Task-lifecycle phase (device-resident as int8)."""

    IDLE = 0
    TO_PICKUP = 1
    TO_DELIVERY = 2


class AgentState(enum.IntEnum):
    """Per-timestep recorded state, reference src/map/agent.rs:9-15 and the
    mapping at src/algorithm/tswap.rs:146-156."""

    IDLE = 0
    PICKING = 1
    CARRYING = 2
    DELIVERED = 3
