"""Tasks and task generation.

Reference: ``Task { pickup, delivery, peer_id, task_id }`` (the only shared
serde struct on the wire, src/map/task_generator.rs:6-12) and
``TaskGeneratorAgent`` which samples random free start/goal pairs
(src/map/task_generator.rs:14-49 via src/map/make_node.rs:31-43).

Differences by design: generation is seeded (the reference uses thread_rng —
unreproducible), and batch generation returns dense (K, 2) index arrays ready
for device upload alongside the dataclass view.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from p2p_distributed_tswap_tpu.core.grid import Grid, Point


@dataclasses.dataclass
class Task:
    pickup: Point
    delivery: Point
    peer_id: Optional[str] = None
    task_id: Optional[int] = None

    def to_json_dict(self) -> dict:
        """Wire form: matches the reference's serde serialization of Task
        (tuples as [x, y] arrays)."""
        return {
            "pickup": [int(self.pickup[0]), int(self.pickup[1])],
            "delivery": [int(self.delivery[0]), int(self.delivery[1])],
            "peer_id": self.peer_id,
            "task_id": None if self.task_id is None else int(self.task_id),
        }

    @staticmethod
    def from_json_dict(d: dict) -> "Task":
        return Task(
            pickup=tuple(d["pickup"]),
            delivery=tuple(d["delivery"]),
            peer_id=d.get("peer_id"),
            task_id=d.get("task_id"),
        )


class TaskGenerator:
    """Seeded random task generator (capability of TaskGeneratorAgent,
    src/map/task_generator.rs:14-49)."""

    def __init__(self, grid: Grid, seed: int = 0):
        self.grid = grid
        self.rng = np.random.default_rng(seed)
        self._free = grid.free_cells()
        assert len(self._free) >= 2, "need at least 2 free cells for a task"
        self._next_id = 0

    def generate_task(self) -> Task:
        i, j = self.rng.choice(len(self._free), size=2, replace=False)
        t = Task(pickup=(int(self._free[i][0]), int(self._free[i][1])),
                 delivery=(int(self._free[j][0]), int(self._free[j][1])),
                 task_id=self._next_id)
        self._next_id += 1
        return t

    def generate_multiple_tasks(self, count: int) -> List[Task]:
        return [self.generate_task() for _ in range(count)]

    def generate_task_arrays(self, count: int) -> np.ndarray:
        """(count, 2) int32 array of [pickup_idx, delivery_idx] flat cell
        indices — the dense form the batched solver consumes."""
        tasks = self.generate_multiple_tasks(count)
        out = np.empty((count, 2), dtype=np.int32)
        for k, t in enumerate(tasks):
            out[k, 0] = self.grid.idx(t.pickup)
            out[k, 1] = self.grid.idx(t.delivery)
        return out

    def generate_distinct_task_arrays(self, count: int,
                                      exclude: Optional[np.ndarray] = None
                                      ) -> np.ndarray:
        """Like :meth:`generate_task_arrays`, but ALL 2*count endpoints are
        distinct cells (optionally also disjoint from ``exclude``, e.g.
        agent start cells).

        Shared endpoints trigger the reference's shared-delivery deadlock
        (Rule-3 swap of identical goals no-ops forever, tswap.rs:197-202) —
        with random endpoints the birthday bound makes that near-certain
        once tasks number in the hundreds, which would starve the
        makespan-parity comparison of oracle-completing seeds
        (analysis/parity_table.py).  Distinct endpoints model the
        warehouse-station setting and keep the *sequential semantics*
        comparable at scale.
        """
        free_idx = np.array([self.grid.idx(p) for p in self._free],
                            dtype=np.int32)
        if exclude is not None and len(exclude):
            free_idx = np.setdiff1d(free_idx, np.asarray(exclude,
                                                         dtype=np.int32))
        need = 2 * count
        assert len(free_idx) >= need, (
            f"{need} distinct endpoints requested but only {len(free_idx)} "
            "eligible free cells")
        cells = self.rng.choice(free_idx, size=need, replace=False)
        self._next_id += count
        return cells.reshape(count, 2).astype(np.int32)


def tasks_to_arrays(grid: Grid, tasks: List[Task]) -> np.ndarray:
    out = np.empty((len(tasks), 2), dtype=np.int32)
    for k, t in enumerate(tasks):
        out[k, 0] = grid.idx(t.pickup)
        out[k, 1] = grid.idx(t.delivery)
    return out
