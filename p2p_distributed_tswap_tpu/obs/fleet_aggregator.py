"""Manager-side fleet aggregation: merge per-peer metrics beacons.

Consumes ``metrics_beacon`` payloads (obs/beacon.py, topic
``mapd.metrics``) from every process in the fleet — Python solverd, the
C++ managers/agents (cpp/common/bus.hpp mirror), busd — and derives the
operator-facing rollup ``analysis/fleet_top.py`` renders:

- per-peer and per-topic bandwidth (wire bytes; rates from the delta
  between consecutive beacons, falling back to the cumulative average
  while only one beacon has arrived);
- tick p50/p95 vs the 500 ms planning budget (``tick_ms`` histogram +
  ``tick.over_budget`` counter, published by solverd's TickRunner and the
  centralized manager's planning tick);
- field-cache hit/recompile rates (solverd counters);
- task-latency percentiles (``task.total_time_ms`` histogram, manager);
- last-seen staleness: a peer whose beacon is older than 3 of its OWN
  advertised beacon intervals (payload ``interval_s``; ``stale_after_s``
  is the fallback for beacons without it) is flagged ``stale`` —
  wedged-but-alive processes surface here, complementing
  runtime/fleet.py's exit-code capture of processes that died outright;
- per-shard bus health (ISSUE 6): a busd pool member's beacon carries
  its ``shard`` index, and its rollup row gains a ``bus`` section —
  relay fanout rate, queued bytes, live peering links, and peering
  traffic — so fleet_top shows each shard's load and the peering tax
  live;
- fleet task throughput (ISSUE 7): a manager beacon's
  ``manager.tasks_dispatched`` / ``manager.tasks_completed`` counter
  pair yields a per-manager ``mgr_tasks`` section (cumulative counts,
  delta-rate ``tasks_per_s`` with the same counter-reset clamp as the
  bandwidth rates, cumulative ``completion_ratio``) and fleet-level
  ``tasks_per_s`` / ``completion_ratio`` — the signals the SLO engine
  (obs/slo.py) judges;
- world-epoch tracking (ISSUE 10 satellite): a peer whose metrics
  beacon carries ``manager.world_seq`` / ``solverd.world_seq`` (and the
  matching ``*.dynamic_world`` flag) gains a per-peer ``world`` section
  — fleet_top's WORLD line renders it, so a dynamic-world-OFF manager
  in a toggling fleet is visible instead of folklore;
- the embedded auditor (ISSUE 10): ``audit_beacon`` payloads (topic
  ``mapd.audit``) feed an :class:`obs.audit.AuditJoiner`; the rollup
  gains an ``audit`` section (verdict, active divergences, per-peer
  epochs) and fleet_top renders the AUDIT verdict line.  Feed audit
  frames through the same :meth:`FleetAggregator.ingest`.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from p2p_distributed_tswap_tpu.obs import audit as _audit
from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.obs.beacon import BEACON_INTERVAL_S
from p2p_distributed_tswap_tpu.obs.registry import hist_quantile, parse_key

STALE_AFTER_S = 3 * BEACON_INTERVAL_S


def _now_ms() -> int:
    return time.time_ns() // 1_000_000


def counter_total(snapshot: dict, name: str) -> float:
    """Sum every series of ``name`` in a beacon snapshot.  Sections may be
    null rather than absent (a foreign emitter with nothing recorded yet),
    hence ``or {}`` throughout."""
    return sum(v for k, v in (snapshot.get("counters") or {}).items()
               if parse_key(k)[0] == name)


def counters_by_label(snapshot: dict, name: str, label: str
                      ) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in (snapshot.get("counters") or {}).items():
        n, labels = parse_key(k)
        if n == name:
            key = labels.get(label, "")
            out[key] = out.get(key, 0.0) + v
    return out


def gauges_by_label(snapshot: dict, name: str, label: str
                    ) -> Dict[str, float]:
    """Latest gauge value per ``label`` series of ``name`` (e.g. the
    mesh solverd's ``solverd.resident_bytes{shard=k}``)."""
    out: Dict[str, float] = {}
    for k, v in (snapshot.get("gauges") or {}).items():
        n, labels = parse_key(k)
        if n == name:
            out[labels.get(label, "")] = v
    return out


def find_hist(snapshot: dict, name: str) -> Optional[dict]:
    """First histogram series of ``name`` (merged across labels if several
    share bucket bounds)."""
    merged: Optional[dict] = None
    for k, h in (snapshot.get("hists") or {}).items():
        if parse_key(k)[0] != name:
            continue
        if merged is None:
            merged = {"buckets": list(h["buckets"]),
                      "counts": list(h["counts"]),
                      "sum": h["sum"], "count": h["count"]}
        elif merged["buckets"] == h["buckets"]:
            merged["counts"] = [a + b for a, b in zip(merged["counts"],
                                                      h["counts"])]
            merged["sum"] += h["sum"]
            merged["count"] += h["count"]
    return merged


class _PeerState:
    __slots__ = ("payload", "last_seen_ms", "prev_metrics", "prev_ts_ms")

    def __init__(self):
        self.payload: dict = {}
        self.last_seen_ms = 0
        self.prev_metrics: Optional[dict] = None
        self.prev_ts_ms = 0


class FleetAggregator:
    """Merge beacons into a live fleet rollup."""

    def __init__(self, budget_ms: float = 500.0,
                 stale_after_s: float = STALE_AFTER_S,
                 on_divergence=None):
        self.budget_ms = budget_ms
        self.stale_after_s = stale_after_s
        self._peers: Dict[str, _PeerState] = {}
        self.beacons_ingested = 0
        # counter-reset evidence (process restarts observed via shrinking
        # cumulative counters; see _rates)
        self.counter_resets = 0
        # embedded auditor (ISSUE 10): audit_beacon payloads route here;
        # rollup() evaluates and exposes the verdict.  on_divergence
        # fires once per confirmed divergence episode (fleet_top's live
        # mode uses it to pull the fleet's black boxes).
        self.audit = _audit.AuditJoiner(on_divergence=on_divergence)
        # replay driver progress (ISSUE 11): newest replay_beacon — the
        # rollup's `replay` section and fleet_top's REPLAY line
        self._replay: Optional[dict] = None
        self._replay_seen_ms = 0
        # control-plane HA (ISSUE 15): takeover announcements observed
        # on mapd.ha — the digest-equal watermark proof, kept for the
        # rollup's `ha` section and the chaos/smoke judges
        self.ha_takeovers: list = []
        # health plane (ISSUE 16): healthd's alert1 records + heartbeat
        # observed on mapd.alert — the rollup's `health` section and
        # fleet_top's HEALTH/ALERT lines
        self.health_alerts: list = []
        self._health_active: Dict[str, dict] = {}
        self._health_beacon: Optional[dict] = None
        self._health_seen_ms = 0

    # cumulative counters watched for restarts (a shrink between two
    # consecutive beacons of one peer = the process restarted with a
    # fresh registry); detection happens HERE, once per beacon pair —
    # counting it in the rate derivations would re-fire on every
    # rollup() call until the next beacon arrived
    _RESET_COUNTERS = ("bus.bytes_sent", "bus.bytes_received",
                       "manager.tasks_completed", "bus.fanout_bytes")

    def ingest(self, payload: dict, now_ms: Optional[int] = None) -> bool:
        """Feed one bus message's data dict; non-beacons are ignored
        (returns False)."""
        if isinstance(payload, dict) \
                and payload.get("type") == "audit_beacon":
            # the embedded auditor's feed (ISSUE 10): digest beacons
            # merge into the joiner, not the metrics peer table
            return self.audit.ingest(payload, now_ms=now_ms)
        if isinstance(payload, dict) \
                and payload.get("type") == "ha_takeover":
            # a promoted standby's announcement (ISSUE 15): carries the
            # takeover watermark and BOTH sides' ledger/view digests —
            # the judge-facing record of the digest-equality acceptance
            rec = dict(payload)
            rec["seen_ms"] = _now_ms() if now_ms is None else now_ms
            self.ha_takeovers.append(rec)
            del self.ha_takeovers[:-16]
            self.beacons_ingested += 1
            return True
        if isinstance(payload, dict) \
                and payload.get("type") == "replay_beacon":
            # the replay driver's progress frames (ISSUE 11): drift vs
            # the captured original, rendered by fleet_top's REPLAY line
            self._replay = payload
            self._replay_seen_ms = _now_ms() if now_ms is None else now_ms
            self.beacons_ingested += 1
            return True
        if isinstance(payload, dict) \
                and payload.get("type") == "alert1":
            # healthd's alert records (ISSUE 16): confirmed breach
            # episodes accumulate as active until their heal lands
            rec = dict(payload)
            rec["seen_ms"] = _now_ms() if now_ms is None else now_ms
            self.health_alerts.append(rec)
            del self.health_alerts[:-32]
            name = str(rec.get("name"))
            if rec.get("kind") == "breach":
                if rec.get("state") == "confirmed":
                    self._health_active[name] = rec
                else:
                    self._health_active.pop(name, None)
            self.beacons_ingested += 1
            return True
        if isinstance(payload, dict) \
                and payload.get("type") == "health_beacon":
            # healthd's per-beat heartbeat (ISSUE 16): watcher liveness
            # for the HEALTH line even on a quiet fleet
            self._health_beacon = payload
            self._health_seen_ms = _now_ms() if now_ms is None else now_ms
            self.beacons_ingested += 1
            return True
        if not isinstance(payload, dict) \
                or payload.get("type") != "metrics_beacon":
            return False
        peer = str(payload.get("peer_id") or payload.get("proc") or "?")
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = _PeerState()
        else:
            st.prev_metrics = st.payload.get("metrics")
            st.prev_ts_ms = st.last_seen_ms
        if st.prev_metrics is not None:
            cur = payload.get("metrics") or {}
            if any(counter_total(cur, c)
                   < counter_total(st.prev_metrics, c)
                   for c in self._RESET_COUNTERS):
                self.counter_resets += 1
                _reg.count("aggregator.counter_resets")
        st.payload = payload
        st.last_seen_ms = _now_ms() if now_ms is None else now_ms
        self.beacons_ingested += 1
        return True

    # -- derivations ------------------------------------------------------
    def _rates(self, st: _PeerState) -> dict:
        cur = st.payload.get("metrics") or {}
        sent = counter_total(cur, "bus.bytes_sent")
        recv = counter_total(cur, "bus.bytes_received")
        if st.prev_metrics is not None and st.last_seen_ms > st.prev_ts_ms:
            dt = (st.last_seen_ms - st.prev_ts_ms) / 1000.0
            d_sent = sent - counter_total(st.prev_metrics, "bus.bytes_sent")
            d_recv = recv - counter_total(st.prev_metrics,
                                          "bus.bytes_received")
            if d_sent < 0 or d_recv < 0:
                # COUNTER RESET: the peer restarted (same peer_id, fresh
                # registry), so cumulative counters shrank and the naive
                # delta would render a negative B/s in fleet_top.  Treat
                # the new snapshot as a fresh baseline: the restart-side
                # totals ARE the traffic since the reset (bounded by the
                # beacon gap), never a negative rate.  (The reset itself
                # is COUNTED in ingest(), once per beacon pair.)
                d_sent, d_recv = sent, recv
        else:  # single beacon so far: cumulative average over uptime
            # `or 0.0`: a foreign emitter can send "uptime_s": null, and
            # max(None, 1e-9) would crash every subsequent rollup
            dt = max(cur.get("uptime_s") or 0.0, 1e-9)
            d_sent, d_recv = sent, recv
        return {
            "bytes_sent": int(sent),
            "bytes_received": int(recv),
            "msgs_sent": int(counter_total(cur, "bus.msgs_sent")),
            "msgs_received": int(counter_total(cur, "bus.msgs_received")),
            "sent_kbps": round(max(0.0, d_sent) * 8.0 / (dt * 1000.0), 3),
            "recv_kbps": round(max(0.0, d_recv) * 8.0 / (dt * 1000.0), 3),
            "by_topic_sent_bytes": {
                k: int(v) for k, v in
                counters_by_label(cur, "bus.bytes_sent", "topic").items()},
        }

    def _mgr_tasks(self, st: _PeerState) -> Optional[dict]:
        """Task-throughput derivation for a manager peer: cumulative
        dispatched/completed, delta-rate tasks/s (counter-reset clamped
        like the bandwidth rates), and the cumulative completion ratio.
        None for peers without the counter pair."""
        cur = st.payload.get("metrics") or {}
        dispatched = counter_total(cur, "manager.tasks_dispatched")
        completed = counter_total(cur, "manager.tasks_completed")
        # queue depth (ISSUE 16): tasks accepted but not yet assigned —
        # dispatch is capacity-gated, so THIS gauge (not the counter
        # pair) is where an overload becomes visible
        pending = (cur.get("gauges") or {}).get("manager.tasks_pending")
        if not dispatched and not completed and pending is None:
            return None
        if st.prev_metrics is not None and st.last_seen_ms > st.prev_ts_ms:
            dt = (st.last_seen_ms - st.prev_ts_ms) / 1000.0
            d_done = completed - counter_total(st.prev_metrics,
                                               "manager.tasks_completed")
            if d_done < 0:
                # counter reset: a restarted manager's fresh totals ARE
                # the completions since the reset (same clamp discipline
                # as _rates — never a negative rate; the reset is
                # counted once, in ingest())
                d_done = completed
        else:  # single beacon so far: cumulative average over uptime
            dt = max(cur.get("uptime_s") or 0.0, 1e-9)
            d_done = completed
        return {
            "dispatched": int(dispatched),
            "completed": int(completed),
            "pending": None if pending is None else int(pending),
            "tasks_per_s": round(max(0.0, d_done) / dt, 3),
            "completion_ratio": (round(completed / dispatched, 4)
                                 if dispatched else None),
        }

    def _peer_rollup(self, st: _PeerState, now_ms: int) -> dict:
        p = st.payload
        m = p.get("metrics") or {}
        age_s = max(0.0, (now_ms - st.last_seen_ms) / 1000.0)
        # staleness paces against the peer's OWN advertised cadence (a peer
        # beaconing every 10 s is healthy at age 8 s); the constructor
        # threshold covers payloads that do not carry interval_s
        interval = p.get("interval_s")
        stale_after = (3.0 * interval
                       if isinstance(interval, (int, float)) and interval > 0
                       else self.stale_after_s)
        tick_hist = find_hist(m, "tick_ms")
        hits = counter_total(m, "solverd.field_cache_hits")
        misses = counter_total(m, "solverd.field_cache_misses")
        task_hist = find_hist(m, "task.total_time_ms")
        out = {
            "proc": p.get("proc", "?"),
            "pid": p.get("pid"),
            "shard": p.get("shard"),  # busd pool member index (ISSUE 6)
            "last_seen_ms": st.last_seen_ms,
            "age_s": round(age_s, 3),
            "stale": age_s > stale_after,
            "uptime_s": m.get("uptime_s"),
            "bandwidth": self._rates(st),
            "tick": None,
            "cache": None,
            "field": None,
            "tasks": None,
            "mgr_tasks": self._mgr_tasks(st),
        }
        if p.get("proc") == "busd":
            # per-shard bus health: fanout rate (delta when a previous
            # beacon exists, else cumulative average), queue depth, and
            # the peering tax
            fan = counter_total(m, "bus.fanout_bytes")
            fan_msgs = counter_total(m, "bus.fanout_msgs")
            if st.prev_metrics is not None \
                    and st.last_seen_ms > st.prev_ts_ms:
                dt = (st.last_seen_ms - st.prev_ts_ms) / 1000.0
                d_fan = fan - counter_total(st.prev_metrics,
                                            "bus.fanout_bytes")
                if d_fan < 0:
                    d_fan = fan  # counter reset: restarted shard
            else:
                dt = max(m.get("uptime_s") or 0.0, 1e-9)
                d_fan = fan
            gauges = m.get("gauges") or {}
            out["bus"] = {
                "fanout_msgs": int(fan_msgs),
                "fanout_kbps": round(max(0.0, d_fan) * 8.0 / (dt * 1000.0),
                                     1),
                "queued_bytes": int(gauges.get("bus.queued_bytes") or 0),
                "clients": int(gauges.get("bus.clients") or 0),
                "peer_links": int(gauges.get("bus.peer_links") or 0),
                "peer_rx_msgs": int(counter_total(m, "bus.peer_rx_msgs")),
                "peer_tx_msgs": int(counter_total(m, "bus.peer_tx_msgs")),
                "slow_consumer_drops": int(
                    counter_total(m, "bus.slow_consumer_drops")),
                "slow_consumer_evictions": int(
                    counter_total(m, "bus.slow_consumer_evictions")),
                # same-host shm lanes + beacon aggregation (ISSUE 18):
                # live lane count, ring traffic both ways, TCP fallbacks
                # (nonzero = rings overflowing), and the coalesce ratio
                # (agg_entries / agg_flushes = beacons per agg1 frame)
                "shm_lanes": int(gauges.get("bus.shm_lanes") or 0),
                "shm_rx_frames": int(counter_total(m, "bus.shm_rx_frames")),
                "shm_tx_frames": int(counter_total(m, "bus.shm_tx_frames")),
                "shm_fallbacks": int(counter_total(m, "bus.shm_fallbacks")),
                "agg_flushes": int(counter_total(m, "bus.agg_flushes")),
                "agg_entries": int(counter_total(m, "bus.agg_entries")),
            }
        if tick_hist and tick_hist["count"]:
            out["tick"] = {
                "count": tick_hist["count"],
                "p50_ms": round(hist_quantile(tick_hist, 0.5), 3),
                "p95_ms": round(hist_quantile(tick_hist, 0.95), 3),
                "budget_ms": self.budget_ms,
                "over_budget": int(counter_total(m, "tick.over_budget")),
            }
        if hits or misses:
            out["cache"] = {
                "hits": int(hits),
                "misses": int(misses),
                "hit_rate": round(hits / (hits + misses), 4),
                "recompiles": int(counter_total(m, "solverd.recompiles")),
            }
        # field-engine health (ISSUE 9): idle-window queue depth + the
        # starvation age gauge, per-cause sweep counters
        # (fresh_goal/prime/repair), incremental-repair counters, and the
        # dynamic-world sequence — solverd beacons only
        gauges = m.get("gauges") or {}
        sweeps = counters_by_label(m, "solverd.field_sweeps", "cause")
        repairs = counter_total(m, "solverd.field_repairs")
        if sweeps or repairs \
                or "solverd.field_queue" in gauges:
            out["field"] = {
                "queue": int(gauges.get("solverd.field_queue") or 0),
                "max_age": int(
                    gauges.get("solverd.field_queue_max_age") or 0),
                "sweeps": {k: int(v) for k, v in sorted(sweeps.items())},
                "repairs": int(repairs),
                "repair_fallbacks": int(
                    counter_total(m, "solverd.field_repair_fallbacks")),
                "promotions": int(
                    counter_total(m, "solverd.field_queue_promotions")),
                "world_seq": int(gauges.get("solverd.world_seq") or 0),
                # host repair-mirror pressure: each eviction turns that
                # goal's next repair into a full recompute, so a rising
                # rate here EXPLAINS a rising repair_fallbacks rate
                "mirror_evictions": int(
                    counter_total(m, "solverd.mirror_evictions")),
            }
            # hierarchical sector planner (ISSUE 19): corridor plans
            # served vs full-sweep fallbacks — only present when
            # JG_SECTOR routed at least one goal
            routes = counter_total(m, "solverd.sector_routes")
            if routes:
                out["field"]["sector"] = {
                    "routes": int(routes),
                    "reentries": int(
                        counter_total(m, "solverd.sector_reentries")),
                    "fallbacks": int(
                        counter_total(m, "solverd.sector_fallbacks")),
                }
        if task_hist and task_hist["count"]:
            out["tasks"] = {
                "completed": task_hist["count"],
                "latency_p50_ms": round(hist_quantile(task_hist, 0.5), 1),
                "latency_p95_ms": round(hist_quantile(task_hist, 0.95), 1),
            }
        # mesh-sharded solverd (ISSUE 13): device count, mesh shape and
        # per-shard resident bytes — the live view of the memory lever
        if gauges.get("solverd.mesh_devices"):
            shapes = gauges_by_label(m, "solverd.mesh_shape", "shape")
            shard_bytes = gauges_by_label(m, "solverd.resident_bytes",
                                          "shard")
            out["mesh"] = {
                "devices": int(gauges["solverd.mesh_devices"]),
                "shape": next(iter(sorted(shapes)), None),
                # numeric shard order (string sort interleaves past 9)
                "resident_bytes": {k: int(v) for k, v in
                                   sorted(shard_bytes.items(),
                                          key=lambda kv: (len(kv[0]),
                                                          kv[0]))},
            }
        # federated world regions (ISSUE 14): a region manager's beacon
        # carries its region id + handoff counters — the REGIONS line's
        # per-region evidence (ownership, pending/acked handoffs)
        if gauges.get("manager.regions"):
            out["federation"] = {
                "region": int(gauges.get("manager.region") or 0),
                "regions": int(gauges["manager.regions"]),
                "handoffs_sent": int(
                    counter_total(m, "manager.handoffs_sent")),
                "handoffs_acked": int(
                    counter_total(m, "manager.handoffs_acked")),
                "handoffs_received": int(
                    counter_total(m, "manager.handoffs_received")),
                "handoffs_dup_dropped": int(
                    counter_total(m, "manager.handoffs_dup_dropped")),
                "retransmits": int(
                    counter_total(m, "manager.handoff_retransmits")),
                "pending": int(
                    gauges.get("manager.fed_pending_handoffs") or 0),
                "mirrors": int(gauges.get("manager.fed_mirrors") or 0),
            }
        # solverd's lane-admission attribution (cause=fresh|handoff)
        admitted = counters_by_label(m, "solverd.lanes_admitted", "cause")
        if admitted:
            out["lanes_admitted"] = {k: int(v)
                                     for k, v in sorted(admitted.items())}
        # control-plane HA (ISSUE 15): role + replication surfaces —
        # the ha_role labeled gauge carries 1 on the CURRENT role, and
        # the replica-lag gauge is the standby's distance behind the
        # active's shipped stream (entries)
        roles = gauges_by_label(m, "manager.ha_role", "role")
        if roles:
            out["ha"] = {
                "role": next((r for r, v in sorted(roles.items()) if v),
                             None),
                "replica_lag": int(
                    gauges.get("manager.ha_replica_lag_entries") or 0),
                "repl_seq": int(gauges.get("manager.ha_repl_seq") or 0),
                "takeovers": int(
                    counter_total(m, "manager.ha_takeovers")),
                "lease_expiries": int(
                    counter_total(m, "manager.ha_lease_expiries")),
                "demotions": int(
                    counter_total(m, "manager.ha_demotions")),
                "restored_lanes": int(
                    counter_total(m, "manager.ha_restored_lanes")),
                "hold_requeues": int(
                    counter_total(m, "manager.ha_hold_requeues")),
            }
        # world-epoch tracking (ISSUE 10 satellite): any peer carrying a
        # world_seq gauge gains a `world` section — the seq AND the
        # dynamic-world flag, so a toggling fleet with an epoch-unaware
        # (dynamic-OFF) manager shows the split on the WORLD line
        wseq = gauges.get("manager.world_seq",
                          gauges.get("solverd.world_seq"))
        wdyn = gauges.get("manager.dynamic_world",
                          gauges.get("solverd.dynamic_world"))
        if wseq is not None or wdyn is not None:
            out["world"] = {
                "seq": int(wseq or 0),
                "dynamic": None if wdyn is None else bool(wdyn),
            }
        return out

    def _replay_rollup(self, now_ms: int) -> Optional[dict]:
        """The replay drift section (ISSUE 11 satellite): progress plus
        tasks/s delta vs the captured original and — once the driver's
        final beacon landed — the per-phase p95 deltas."""
        if self._replay is None:
            return None
        age_s = max(0.0, (now_ms - self._replay_seen_ms) / 1000.0)
        if age_s > 60.0:
            # the driver beacons every ~2 s and exits after its final
            # frame: a minute-old section is a FINISHED (or dead) replay
            # — drop it so a long-lived fleet_top stops rendering stale
            # replay numbers against live traffic
            self._replay = None
            return None
        p = self._replay
        out = {k: p.get(k) for k in
               ("capture_source", "t_s", "injected", "total",
                "world_injected", "done", "done_dups", "tasks_per_s",
                "orig_tasks_per_s", "final")}
        out["age_s"] = round(age_s, 1)
        now_tps, orig_tps = p.get("tasks_per_s"), p.get("orig_tasks_per_s")
        if isinstance(now_tps, (int, float)) \
                and isinstance(orig_tps, (int, float)):
            out["tasks_per_s_delta"] = round(now_tps - orig_tps, 3)
        for k in ("drift_pct", "phase_p95_delta_ms"):
            if p.get(k) is not None:
                out[k] = p[k]
        return out

    def _health_rollup(self, now_ms: int) -> Optional[dict]:
        """The health plane section (ISSUE 16): healthd's heartbeat +
        the still-active confirmed breach episodes.  None until the
        first health frame — "no watcher" must read unknown, never a
        silent green."""
        if self._health_beacon is None and not self.health_alerts:
            return None
        beacon = self._health_beacon
        stale = None
        if beacon is not None:
            age_s = max(0.0, (now_ms - self._health_seen_ms) / 1000.0)
            interval = beacon.get("interval_s") or 2.0
            stale = age_s > 3 * float(interval) + 2.0
        return {
            "beacon": beacon,
            "stale": stale,
            "active": [self._health_active[k]
                       for k in sorted(self._health_active)],
            "alerts": len(self.health_alerts),
            "last": (self.health_alerts[-1]
                     if self.health_alerts else None),
        }

    def rollup(self, now_ms: Optional[int] = None) -> dict:
        """The fleet-wide snapshot fleet_top renders / dumps as JSON."""
        now_ms = _now_ms() if now_ms is None else now_ms
        # audit judgment rides the rollup cadence (~ the beacon
        # interval): streak thresholds confirm sustained divergences
        if self.audit.beacons:
            self.audit.evaluate(now_ms)
        peers = {peer: self._peer_rollup(st, now_ms)
                 for peer, st in sorted(self._peers.items())}
        ticks = [p["tick"] for p in peers.values() if p["tick"]]
        # fleet task throughput: summed over every manager peer (one in
        # centralized fleets; completion_ratio stays None until a
        # dispatch counter arrives — absence must read unknown, not 0)
        mgr = [p["mgr_tasks"] for p in peers.values() if p["mgr_tasks"]]
        dispatched = sum(t["dispatched"] for t in mgr)
        completed = sum(t["completed"] for t in mgr)
        pending = [t["pending"] for t in mgr
                   if t.get("pending") is not None]
        # federated regions (ISSUE 14): one row per region manager —
        # per-region tasks/s + the handoff ledger the REGIONS line shows
        fed_peers = [(peer, p) for peer, p in peers.items()
                     if p.get("federation")]
        # a restarted region manager leaves its dead incarnation's
        # beacons in the window (marked stale) while the fresh peer
        # beacons the SAME region id: prefer the live row — a stale one
        # must never shadow it (and must not inflate the manager count)
        live_regions = {p["federation"]["region"]
                        for _, p in fed_peers if not p["stale"]}
        fed_peers = [(peer, p) for peer, p in fed_peers
                     if not (p["stale"]
                             and p["federation"]["region"] in live_regions)]
        federation = None
        if fed_peers:
            per_region = {}
            for peer, p in fed_peers:
                f = p["federation"]
                t = p.get("mgr_tasks") or {}
                per_region[f"r{f['region']}"] = {
                    "peer": peer,
                    "stale": p["stale"],
                    "tasks_per_s": t.get("tasks_per_s"),
                    "dispatched": t.get("dispatched"),
                    "completed": t.get("completed"),
                    "pending_handoffs": f["pending"],
                    "handoffs_sent": f["handoffs_sent"],
                    "handoffs_acked": f["handoffs_acked"],
                    "handoffs_dup_dropped": f["handoffs_dup_dropped"],
                    "mirrors": f["mirrors"],
                }
            federation = {
                "regions": max(p["federation"]["regions"]
                               for _, p in fed_peers),
                "managers": len(fed_peers),
                "per_region": dict(sorted(
                    per_region.items(), key=lambda kv: int(kv[0][1:]))),
                "handoffs_sent": sum(p["federation"]["handoffs_sent"]
                                     for _, p in fed_peers),
                "handoffs_acked": sum(p["federation"]["handoffs_acked"]
                                      for _, p in fed_peers),
                "handoffs_dup_dropped": sum(
                    p["federation"]["handoffs_dup_dropped"]
                    for _, p in fed_peers),
                "pending": sum(p["federation"]["pending"]
                               for _, p in fed_peers),
            }
        # control-plane HA (ISSUE 15): live-role census across manager
        # peers + the newest observed takeover announcement.  Stale
        # rows keep their last-beaconed role — a SIGKILLed active's row
        # reads active+stale, which is exactly the operator's evidence.
        ha_peers = [(peer, p) for peer, p in peers.items()
                    if p.get("ha")]
        ha = None
        if ha_peers or self.ha_takeovers:
            ha = {
                "active": sorted(peer for peer, p in ha_peers
                                 if p["ha"]["role"] == "active"
                                 and not p["stale"]),
                "standby": sorted(peer for peer, p in ha_peers
                                  if p["ha"]["role"] == "standby"
                                  and not p["stale"]),
                "replica_lag": max((p["ha"]["replica_lag"]
                                    for _, p in ha_peers), default=0),
                "takeovers": sum(p["ha"]["takeovers"]
                                 for _, p in ha_peers),
                "lease_expiries": sum(p["ha"]["lease_expiries"]
                                      for _, p in ha_peers),
                "demotions": sum(p["ha"]["demotions"]
                                 for _, p in ha_peers),
                "last_takeover": (self.ha_takeovers[-1]
                                  if self.ha_takeovers else None),
            }
        return {
            "ts_ms": now_ms,
            "budget_ms": self.budget_ms,
            "beacons_ingested": self.beacons_ingested,
            # None until the first audit beacon: "no auditor evidence"
            # must read unknown, never a silent green
            "audit": self.audit.status() if self.audit.beacons else None,
            "replay": self._replay_rollup(now_ms),
            "federation": federation,
            "ha": ha,
            "health": self._health_rollup(now_ms),
            "peers": peers,
            "fleet": {
                "peers": len(peers),
                "counter_resets": self.counter_resets,
                "stale_peers": sum(1 for p in peers.values() if p["stale"]),
                "bytes_sent": sum(p["bandwidth"]["bytes_sent"]
                                  for p in peers.values()),
                "bytes_received": sum(p["bandwidth"]["bytes_received"]
                                      for p in peers.values()),
                "ticks": sum(t["count"] for t in ticks),
                "ticks_over_budget": sum(t["over_budget"] for t in ticks),
                "tasks_dispatched": dispatched if mgr else None,
                "tasks_completed": completed if mgr else None,
                # None when no manager exports the gauge: queue-depth
                # absence must read unknown, never an empty queue
                "tasks_pending": sum(pending) if pending else None,
                "tasks_per_s": (round(sum(t["tasks_per_s"] for t in mgr), 3)
                                if mgr else None),
                "completion_ratio": (round(completed / dispatched, 4)
                                     if dispatched else None),
            },
        }
