"""Runtime observability: unified live-metrics registry (obs.registry),
span tracer (obs.trace), per-tick heartbeat (obs.heartbeat), metrics
beacons (obs.beacon), manager-side fleet aggregation
(obs.fleet_aggregator), cross-process task-causality events (obs.events,
trace-context propagation + Perfetto flows + hop-latency histograms), and
the always-on flight-recorder black box (obs.flightrec).

Counters/gauges/histograms are ALWAYS on (one dict op each) and flow into
every read side — Prometheus ``/metrics`` (JG_METRICS_PORT), the periodic
``mapd.metrics`` bus beacon, stats dumps, and trace-file counter events.
Span tracing stays gated by JG_TRACE=1 (near-zero cost off).  The C++ host
runtime mirrors the span schema in cpp/common/trace.hpp and the registry +
beacon in cpp/common/metrics.hpp / bus.hpp; merged trace reports come from
analysis/trace_report.py, the live fleet view from analysis/fleet_top.py.
"""

from p2p_distributed_tswap_tpu.obs import events  # noqa: F401
from p2p_distributed_tswap_tpu.obs import flightrec  # noqa: F401
from p2p_distributed_tswap_tpu.obs import registry  # noqa: F401
from p2p_distributed_tswap_tpu.obs import trace  # noqa: F401
from p2p_distributed_tswap_tpu.obs.heartbeat import (  # noqa: F401
    TICK_BUDGET_MS,
    HeartbeatWriter,
)
