"""Runtime observability: span tracer (obs.trace), per-tick heartbeat
(obs.heartbeat).  Enabled with JG_TRACE=1; near-zero-cost when off.  The
C++ host runtime mirrors the span schema in cpp/common/trace.hpp; merged
reports come from analysis/trace_report.py."""

from p2p_distributed_tswap_tpu.obs import trace  # noqa: F401
from p2p_distributed_tswap_tpu.obs.heartbeat import (  # noqa: F401
    TICK_BUDGET_MS,
    HeartbeatWriter,
)
