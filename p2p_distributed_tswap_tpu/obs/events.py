"""Structured task-lifecycle events with cross-process causal context.

The span tracer (obs/trace.py) answers "where does THIS process spend its
time"; this module answers the fleet question — *where does a task's
latency go* — by emitting one structured event per lifecycle hop (dispatch,
claim, pickup, delivery, done, done-ack, swap legs, plan frames), each
carrying the trace context that rode the triggering message:

- ``trace_id``: rooted at task creation (manager: run-epoch << 32 | task
  id) or at a plan chain; the same id appears in every process the task
  touches, so ``analysis/task_timeline.py`` can reconstruct the causal
  timeline from merged per-process event logs;
- ``hop``: a monotone wire-crossing counter (each SEND increments it), the
  happens-before order when wall clocks disagree;
- ``send_ms``: the sender's wall clock at publish time — the receive side
  derives a clock-skew-clamped one-way latency histogram per edge
  (``hop_latency_ms{edge=...}``, the same clamp discipline as the PR-1
  task-metric derivations; raw negatives count ``hop.clock_skew_events``).

Event sinks, in cost order:

1. the flight-recorder ring (obs/flightrec.py) — ALWAYS on;
2. hop-latency registry histograms — always on when a ``send_ms`` rode in;
3. with ``JG_TRACE=1`` and the trace_id sampled in: a write-through line in
   ``$JG_TRACE_DIR/<proc>-<pid>.events.jsonl`` (task-lifecycle rates are a
   few events per task, so per-event appends are noise) plus a Perfetto
   *flow* event in the span tracer, so ``trace_report.py --perfetto``
   renders cross-process arrows along each task's journey.

Wire format (JSON messages): ``"tc": [trace_id, hop, send_ms]``.  The
packed codecs carry the same triple natively (plan_codec trace1 blocks).

Environment:
  JG_TRACE_CTX=0        kill switch — no context goes on the wire (bytes
                        identical to the pre-trace1 format) and
                        trace-correlated events are suppressed on BOTH
                        send and receive sides (no registry hop
                        latencies, no event files, no flows).  Context-
                        free events (bus membership, crashes) still
                        reach the flight ring — the black box stays on.
  JG_TRACE_SAMPLE=F     fraction of trace_ids that emit event-log/flow
                        records (default 1.0).  Sampling is DETERMINISTIC
                        on trace_id (mod-997 residue, mirrored in
                        cpp/common/events.hpp) so a task's whole timeline
                        is either fully sampled or fully skipped — a
                        partially sampled timeline would read as gaps.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

from p2p_distributed_tswap_tpu.obs import flightrec
from p2p_distributed_tswap_tpu.obs import registry as _reg
from p2p_distributed_tswap_tpu.obs import trace as _trace

SAMPLE_MOD = 997  # prime: sequential task ids cycle all residues uniformly

# clamp ceiling for one-way latency: beyond this the pair of stamps is
# evidence of clock trouble, not a real wire delay
HOP_CLAMP_MAX_MS = 60_000.0


def ctx_enabled() -> bool:
    return os.environ.get("JG_TRACE_CTX", "1") not in ("0", "false", "")


def sample_rate() -> float:
    try:
        return float(os.environ.get("JG_TRACE_SAMPLE", "1.0"))
    except ValueError:
        return 1.0


def sampled(trace_id: int) -> bool:
    """Deterministic per-trace sampling decision (mirrored byte-for-byte by
    cpp/common/events.hpp: same modulus, same threshold rounding)."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (int(trace_id) % SAMPLE_MOD) < int(rate * SAMPLE_MOD)


def now_ms() -> int:
    return time.time_ns() // 1_000_000


def make_tc(trace_id: int, hop: int,
            send_ms: Optional[int] = None) -> List[int]:
    """The JSON-wire trace context: ``[trace_id, hop, send_ms]``."""
    return [int(trace_id), int(hop),
            now_ms() if send_ms is None else int(send_ms)]


def parse_tc(msg: dict) -> Optional[Tuple[int, int, int]]:
    """``(trace_id, hop, send_ms)`` from a message's ``tc`` field, or None
    (absent/malformed — legacy peers simply don't carry it)."""
    tc = msg.get("tc")
    if not isinstance(tc, (list, tuple)) or len(tc) != 3:
        return None
    try:
        return int(tc[0]), int(tc[1]), int(tc[2])
    except (TypeError, ValueError):
        return None


def hop_latency_ms(send_ms: int, recv_ms: Optional[int] = None,
                   edge: str = "") -> float:
    """Clock-skew-clamped one-way latency, recorded into the registry
    (``hop_latency_ms{edge=...}``); raw negatives count
    ``hop.clock_skew_events`` so the clamp is never silent."""
    recv = now_ms() if recv_ms is None else recv_ms
    raw = float(recv - send_ms)
    if raw < 0:
        _reg.count("hop.clock_skew_events")
        _reg.gauge("hop.clock_skew_worst_ms",
                   max(-raw, _reg.get_registry().gauge_value(
                       "hop.clock_skew_worst_ms", 0.0)))
    lat = min(max(raw, 0.0), HOP_CLAMP_MAX_MS)
    if edge:
        _reg.observe("hop_latency_ms", lat, edge=edge)
    return lat


class EventLog:
    """Per-process lifecycle-event emitter (see module docstring)."""

    def __init__(self, proc: str = "py"):
        self.proc = proc
        self.pid = os.getpid()
        self._file = None
        self._file_path = None
        self.emitted = 0

    def _events_path(self) -> str:
        return os.path.join(_trace.trace_dir(),
                            f"{self.proc}-{self.pid}.events.jsonl")

    def _write_line(self, line: str) -> None:
        path = self._events_path()
        if self._file is None or self._file_path != path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = open(path, "a")
            self._file_path = path
        self._file.write(line + "\n")
        self._file.flush()

    def emit(self, event: str, trace_id: Optional[int] = None,
             hop: Optional[int] = None, task_id: Optional[int] = None,
             send_ms: Optional[int] = None, peer: Optional[str] = None,
             **extra) -> None:
        """One lifecycle event.  ``send_ms`` is the TRIGGERING message's
        sender stamp (present exactly when this event is the receive side
        of a wire hop).  The JG_TRACE_CTX kill switch suppresses
        trace-correlated events entirely (see module docstring)."""
        if trace_id is not None and not ctx_enabled():
            return
        ts = now_ms()
        ev = {"ts_ms": ts, "proc": self.proc, "pid": self.pid,
              "event": event}
        if trace_id is not None:
            ev["trace_id"] = int(trace_id)
        if hop is not None:
            ev["hop"] = int(hop)
        if task_id is not None:
            ev["task_id"] = int(task_id)
        if peer is not None:
            ev["peer"] = peer
        if send_ms is not None:
            ev["send_ms"] = int(send_ms)
            ev["wire_ms"] = round(hop_latency_ms(send_ms, ts, edge=event), 3)
        if extra:
            ev.update(extra)
        flightrec.record(ev)
        self.emitted += 1
        _reg.count("events.emitted", event=event)
        if trace_id is None or not _trace.enabled() \
                or not sampled(trace_id):
            return
        try:
            self._write_line(json.dumps(ev))
        except OSError:
            _reg.count("events.write_errors")
        # Perfetto flow event: constant name/cat, id = trace_id — the JSON
        # importer links s/t/f steps of one id into cross-process arrows
        phase = "t"
        if event == "task.dispatch" and (hop is None or hop <= 1):
            phase = "s"
        elif event.endswith("done_ack"):
            phase = "f"
        _trace.flow("task", trace_id, phase, step=event)

    def close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None


_log = EventLog()


def get_log() -> EventLog:
    return _log


def configure(proc: str) -> EventLog:
    """Rebuild the process event log under its role name (process entry /
    test isolation), alongside flightrec.configure / trace.configure."""
    global _log
    _log.close()
    _log = EventLog(proc=proc)
    return _log


def emit(event: str, **kw) -> None:
    _log.emit(event, **kw)
