"""Flight recorder: an always-on black box of the last N structured events.

Every process keeps a fixed-size, lock-light ring of the most recent
structured lifecycle events (obs/events.py feeds it; the C++ mirror is
cpp/common/flightrec.hpp).  Unlike span tracing — opt-in, high-volume,
flushed on a cadence — the flight ring is ALWAYS recording and costs one
deque append under a lock per event, so when a process crashes, wedges, or
an e2e run fails, the fleet's last seconds are reconstructable even though
nobody asked for a trace beforehand (exactly the aviation black-box
contract; ``analysis/blackbox.py`` prints the merged fleet view).

Dump triggers:
- SIGUSR2 (``install()`` wires the handler; SIGUSR1 stays the stats dump);
- process exit (atexit) and unhandled exceptions (sys.excepthook chain);
- a bus ``flight_dump`` request (each daemon's message loop calls
  :func:`dump` and answers with the path);
- an e2e test failure (the pytest fixture collects the dumped files).

Dumps land in ``$JG_FLIGHT_DIR`` (the fleet runner points this at its
per-run log dir) or, unset, next to the trace files (``JG_TRACE_DIR``,
default ``results/trace``), as ``<proc>-<pid>.flight.jsonl`` — one event
object per line, newest last, plus a leading meta line.
"""

from __future__ import annotations

import atexit
import collections
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

DEFAULT_CAPACITY = 4096


def flight_dir() -> str:
    d = os.environ.get("JG_FLIGHT_DIR", "")
    if d:
        return d
    return os.environ.get("JG_TRACE_DIR", "results/trace")


class FlightRecorder:
    """Bounded ring of structured events; thread-safe, always on."""

    def __init__(self, proc: str = "py", capacity: int = DEFAULT_CAPACITY):
        self.proc = proc
        self.pid = os.getpid()
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self.dumps = 0

    def record(self, event: dict) -> None:
        with self._lock:
            self._ring.append(event)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def tail(self, n: Optional[int] = None) -> list:
        with self._lock:
            evs = list(self._ring)
        return evs if n is None else evs[-n:]

    def default_path(self) -> str:
        return os.path.join(flight_dir(),
                            f"{self.proc}-{self.pid}.flight.jsonl")

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Write the ring (oldest first) as JSONL; returns the path, or
        None when the write failed — a black box must never take the
        process down with it."""
        path = path or self.default_path()
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            evs = self.tail()
            with open(path, "w") as f:
                f.write(json.dumps({
                    "meta": "flight", "proc": self.proc, "pid": self.pid,
                    "reason": reason, "events": len(evs),
                    "dumped_ms": time.time_ns() // 1_000_000}) + "\n")
                for ev in evs:
                    f.write(json.dumps(ev) + "\n")
            self.dumps += 1
            return path
        except OSError:
            return None


_recorder = FlightRecorder()
_installed = False


def get_recorder() -> FlightRecorder:
    return _recorder


def record(event: dict) -> None:
    _recorder.record(event)


def dump(path: Optional[str] = None, reason: str = "manual"
         ) -> Optional[str]:
    return _recorder.dump(path, reason)


def configure(proc: str, capacity: int = DEFAULT_CAPACITY
              ) -> FlightRecorder:
    """Rebuild the process recorder under its role name (call at process
    entry, like trace.configure)."""
    global _recorder
    _recorder = FlightRecorder(proc=proc, capacity=capacity)
    return _recorder


def install(proc: Optional[str] = None) -> FlightRecorder:
    """Arm the dump triggers for a daemon process: SIGUSR2, process exit,
    and unhandled exceptions.  Idempotent per process; safe to call from
    non-main threads only for the atexit part (signal handlers require the
    main thread, so those are skipped there)."""
    global _installed
    if proc:
        configure(proc)
    if _installed:
        return _recorder
    _installed = True
    atexit.register(lambda: _recorder.dump(reason="exit"))

    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        _recorder.record({"ts_ms": time.time_ns() // 1_000_000,
                          "proc": _recorder.proc, "pid": _recorder.pid,
                          "event": "crash.exception",
                          "error": f"{tp.__name__}: {val}"})
        _recorder.dump(reason="exception")
        prev_hook(tp, val, tb)

    sys.excepthook = hook
    if threading.current_thread() is threading.main_thread():
        try:
            signal.signal(
                signal.SIGUSR2,
                lambda *_: _recorder.dump(reason="sigusr2"))
        except (ValueError, OSError):
            pass  # embedded interpreters without signal support
    return _recorder
