"""Continuous fleet health plane (ISSUE 16): rollup history, multi-window
SLO burn rates, breach forecasting, and per-component attribution.

Every SLO surface before this PR is point-in-time — obs/slo.py judges
one saved signals blob, fleet_top renders the instantaneous rollup —
so nothing watches *trends*, predicts a breach before it lands, or
emits a machine-readable alert an autoscaler could act on.  This module
is that watcher, following the multi-window burn-rate discipline of the
SRE Workbook alerting chapter and the durable-rollup-history shape of
Monarch (VLDB '20):

- **the history ring** — :class:`HealthRing` samples the fleet rollup's
  flattened signals every evaluation beat into bounded, versioned
  ``health1`` records (same STRICT version discipline as ``capture1`` /
  ``ledger1``: any other version string is REJECTED, never
  half-interpreted), optionally persisted to an on-disk jsonl that is
  compacted in place once it doubles its capacity;
- **multi-window burn rates** — per SLO, the FAST window (default 3
  samples) confirms: every sample in it must breach, sustained for a
  fresh-evidence confirm streak (the auditor's episode idiom — one
  transient sample never alerts), while the SLOW window (default 12)
  de-flaps: a confirmed episode only heals once the slow window is
  clean, and a healed episode re-arms so a NEW breach re-confirms;
- **breach forecasting** — :class:`SlopeForecaster` keeps an EWMA of
  each signal's level, slope, and slope residual; a sustained monotone
  trend toward a threshold emits "crosses its SLO in ~45 s" with the
  forecast lead and a residual-gated confidence.  Flat, noisy, and
  step inputs must never forecast — the residual EWMA tracks exactly
  the evidence that the slope is NOT a trend;
- **attribution** — each alert names the driving component by diffing
  the rollup's per-shard (``bus``), per-region (``federation``),
  per-tenant (audit ``ns``) and per-peer sections, and carries a
  ``recommendation`` (direction + actuator hint out of
  ``spawn_shard``/``kill_shard``/``split_region``/``merge_regions``/
  ``evict_tenant``/``shed_load``) — the wire contract handed to
  ROADMAP item 1's future actuation daemon.

Alerts publish as versioned ``alert1`` records on the raw
``mapd.alert`` topic and append to ``<record dir>/healthd.alerts.jsonl``
(``analysis/blackbox.py --alerts`` merges them into the post-mortem
readout); a confirmed page-severity breach triggers the auditor's
auto-capture path, so every page ships with a replayable ``capture1``
regression artifact.

``JG_HEALTH`` unset/0 is the kill switch (HA idiom, default OFF): no
fleet component subscribes ``mapd.alert`` and the wire stays
byte-identical (live raw-socket pin test in tests/test_health.py).
The standalone runner is the explicit opt-in:

    JG_HEALTH=1 python -m p2p_distributed_tswap_tpu.obs.health \\
        --port 7400 [--record DIR] [--spec FILE] [--for 60]
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Callable, Deque, Dict, List, Optional, Tuple

from p2p_distributed_tswap_tpu.obs import slo as _slo

ALERT_TOPIC = "mapd.alert"
HEALTH_VERSION = "health1"
ALERT_VERSION = "alert1"
KILL_ENV = "JG_HEALTH"
INTERVAL_ENV = "JG_HEALTH_INTERVAL_S"
# sample the rollup every beacon interval: evaluating faster only
# re-reads the same beacons (fresh-evidence gating would skip anyway)
HEALTH_INTERVAL_S = 2.0

FAST_WINDOW = 3     # samples — ALL must breach before an episode confirms
SLOW_WINDOW = 12    # samples — ALL must be clean before an episode heals
CONFIRM_STREAK = 2  # fresh-evidence evaluation rounds (auditor idiom)

FORECAST_MIN_SAMPLES = 5
FORECAST_CONFIDENCE = 0.5
FORECAST_HORIZON_S = 180.0
EWMA_ALPHA = 0.35

RING_CAP = 512

SEVERITY_PAGE = "page"
SEVERITY_WARN = "warn"
ALERT_KINDS = ("breach", "forecast")
ALERT_STATES = ("confirmed", "healed")

ACTUATORS = ("spawn_shard", "kill_shard", "split_region",
             "merge_regions", "evict_tenant", "shed_load")


def enabled() -> bool:
    """The health plane is OFF unless JG_HEALTH is set truthy — the
    default keeps the wire byte-identical to the pre-health build."""
    return os.environ.get(KILL_ENV, "") not in ("", "0")


def interval_s() -> float:
    try:
        return float(os.environ.get(INTERVAL_ENV, "")
                     or HEALTH_INTERVAL_S)
    except ValueError:
        return HEALTH_INTERVAL_S


def _now_ms() -> int:
    return time.time_ns() // 1_000_000


# ---------------------------------------------------------------------------
# health1 / alert1 records — STRICT versioned codecs (capture1 discipline)
# ---------------------------------------------------------------------------

class HealthError(ValueError):
    """Malformed health1/alert1 record (bad version, shape, or field)."""


def validate_health(rec: dict) -> dict:
    """Validate one ``health1`` ring record.  Raises
    :class:`HealthError` on anything a reader could misinterpret —
    including any version other than ``health1``: an unknown schema
    must be REJECTED, never half-read."""
    if not isinstance(rec, dict):
        raise HealthError("health record must be a JSON object")
    version = rec.get("version")
    if version != HEALTH_VERSION:
        raise HealthError(
            f"unsupported health version {version!r} "
            f"(this build reads {HEALTH_VERSION!r} only)")
    for k in ("ts_ms", "seq"):
        if not isinstance(rec.get(k), int):
            raise HealthError(f"health.{k} missing or not an int")
    if not isinstance(rec.get("signals"), dict):
        raise HealthError("health.signals missing or not an object")
    for k in ("failed", "unknown"):
        if not isinstance(rec.get(k, []), list):
            raise HealthError(f"health.{k} must be a list")
    return rec


def validate_alert(rec: dict) -> dict:
    """Validate one ``alert1`` record — the wire contract the future
    actuation daemon consumes, so every field it routes on is checked
    here, with the same strict-version rule as ``health1``."""
    if not isinstance(rec, dict):
        raise HealthError("alert must be a JSON object")
    version = rec.get("version")
    if version != ALERT_VERSION:
        raise HealthError(
            f"unsupported alert version {version!r} "
            f"(this build reads {ALERT_VERSION!r} only)")
    if not isinstance(rec.get("ts_ms"), int):
        raise HealthError("alert.ts_ms missing or not an int")
    for k in ("name", "signal"):
        if not isinstance(rec.get(k), str) or not rec[k]:
            raise HealthError(f"alert.{k} missing or empty")
    if rec.get("kind") not in ALERT_KINDS:
        raise HealthError(f"alert.kind {rec.get('kind')!r} not in "
                          f"{ALERT_KINDS}")
    if rec.get("state") not in ALERT_STATES:
        raise HealthError(f"alert.state {rec.get('state')!r} not in "
                          f"{ALERT_STATES}")
    if rec.get("severity") not in (SEVERITY_PAGE, SEVERITY_WARN):
        raise HealthError(f"alert.severity {rec.get('severity')!r} "
                          "must be page or warn")
    reco = rec.get("recommendation")
    if reco is not None:
        if not isinstance(reco, dict) \
                or reco.get("actuator") not in ACTUATORS \
                or reco.get("direction") not in ("up", "down"):
            raise HealthError(
                "alert.recommendation needs a known actuator "
                f"({'/'.join(ACTUATORS)}) and an up/down direction")
    fc = rec.get("forecast")
    if fc is not None:
        if not isinstance(fc, dict) \
                or not isinstance(fc.get("eta_s"), (int, float)) \
                or not isinstance(fc.get("confidence"), (int, float)):
            raise HealthError(
                "alert.forecast needs numeric eta_s and confidence")
    return rec


class HealthRing:
    """Bounded time-series of ``health1`` records — the durable rollup
    history the forecaster extrapolates over.  With a ``path`` the ring
    persists as append-only jsonl, compacted in place once the file
    doubles the cap (append stays O(1) amortized; a crash loses at most
    the compaction window, never corrupts — every load re-validates)."""

    def __init__(self, path: Optional[str] = None, cap: int = RING_CAP):
        self.path = str(path) if path else None
        self.cap = max(2, int(cap))
        self.records: Deque[dict] = collections.deque(maxlen=self.cap)
        self._file_lines = 0
        if self.path and os.path.exists(self.path):
            for rec in self.load(self.path):
                self.records.append(rec)
            self._file_lines = len(self.records)

    def append(self, rec: dict) -> dict:
        validate_health(rec)
        self.records.append(rec)
        if self.path:
            if self._file_lines >= 2 * self.cap:
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    for r in self.records:
                        f.write(json.dumps(r) + "\n")
                os.replace(tmp, self.path)
                self._file_lines = len(self.records)
            else:
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
                self._file_lines += 1
        return rec

    @staticmethod
    def load(path) -> List[dict]:
        """Read + validate a persisted ring.  A malformed or
        wrong-version record raises :class:`HealthError` — history a
        forecaster would silently misread is worse than no history."""
        out = []
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    raise HealthError(
                        f"{path}:{i + 1}: not JSON: {e}") from None
                out.append(validate_health(rec))
        return out


# ---------------------------------------------------------------------------
# EWMA-slope breach forecasting
# ---------------------------------------------------------------------------

class SlopeForecaster:
    """EWMA level/slope/residual tracker for one signal.

    ``observe`` feeds ``(t_s, value)`` samples; ``forecast`` answers
    "does the current trend cross ``threshold`` within the horizon, and
    how sure are we".  Confidence is ``1 - residual/|slope|``: a
    monotone ramp drives the residual toward zero (confidence → 1),
    while flat series have no slope, noisy series carry residual ≥
    |slope|, and a step spikes the residual exactly when it spikes the
    slope — none of them forecast."""

    def __init__(self, alpha: float = EWMA_ALPHA,
                 min_samples: int = FORECAST_MIN_SAMPLES,
                 horizon_s: float = FORECAST_HORIZON_S,
                 min_confidence: float = FORECAST_CONFIDENCE):
        self.alpha = alpha
        self.min_samples = min_samples
        self.horizon_s = horizon_s
        self.min_confidence = min_confidence
        self.value: Optional[float] = None
        self.slope = 0.0   # EWMA of per-second deltas
        self.resid = 0.0   # EWMA of |delta - slope| (trend noise)
        self.n = 0
        self._last_t: Optional[float] = None

    def observe(self, t_s: float, value: float) -> None:
        if self.value is None or self._last_t is None:
            self.value, self._last_t, self.n = float(value), t_s, 1
            return
        dt = t_s - self._last_t
        if dt <= 0:
            return
        d = (float(value) - self.value) / dt
        a = self.alpha
        # residual against the PRE-update slope: a step's huge delta
        # lands in the residual in the same beat it lands in the slope
        self.resid = (1 - a) * self.resid + a * abs(d - self.slope)
        self.slope = (1 - a) * self.slope + a * d
        self.value, self._last_t = float(value), t_s
        self.n += 1

    def confidence(self) -> float:
        if abs(self.slope) < 1e-12:
            return 0.0
        return max(0.0, min(1.0, 1.0 - self.resid / abs(self.slope)))

    def forecast(self, threshold: float,
                 bound: str) -> Optional[dict]:
        """Crossing prediction for a ``max`` bound (value climbing into
        it) or a ``min`` bound (value falling out of it).  None unless
        the trend is sustained, monotone toward the bound, confident,
        and lands inside the horizon."""
        if self.value is None or self.n < self.min_samples:
            return None
        if bound == "max":
            if self.slope <= 0 or self.value > threshold:
                return None
        elif bound == "min":
            if self.slope >= 0 or self.value < threshold:
                return None
        else:
            return None
        conf = self.confidence()
        if conf < self.min_confidence:
            return None
        eta_s = (threshold - self.value) / self.slope
        if not (0.0 < eta_s <= self.horizon_s):
            return None
        return {"eta_s": round(eta_s, 1),
                "confidence": round(conf, 3),
                "slope_per_s": round(self.slope, 6)}


# ---------------------------------------------------------------------------
# attribution: name the driving component, recommend an actuator
# ---------------------------------------------------------------------------

def _counter(d: Optional[dict], k: str) -> float:
    v = (d or {}).get(k)
    return float(v) if isinstance(v, (int, float)) else 0.0


def _hot_shard(rollup: dict, prev: Optional[dict]) -> Optional[dict]:
    """The busd pool member under the most pressure: shed/eviction
    growth first (actual harm), then queue depth, then fanout load."""
    prev_bus = {peer: p.get("bus")
                for peer, p in ((prev or {}).get("peers") or {}).items()}
    best, best_score = None, 0.0
    for peer, p in (rollup.get("peers") or {}).items():
        bus = p.get("bus")
        if not bus:
            continue
        pb = prev_bus.get(peer)
        shed = (_counter(bus, "slow_consumer_drops")
                + _counter(bus, "slow_consumer_evictions")
                - _counter(pb, "slow_consumer_drops")
                - _counter(pb, "slow_consumer_evictions"))
        score = (max(0.0, shed) * 1e6
                 + _counter(bus, "queued_bytes")
                 + _counter(bus, "fanout_kbps"))
        if score > best_score:
            shard = p.get("shard")
            best_score = score
            best = {"kind": "bus_shard",
                    "id": f"s{shard}" if shard is not None else peer,
                    "peer": peer, "proc": p.get("proc"),
                    "detail": (f"q={int(_counter(bus, 'queued_bytes'))}B"
                               f" shed=+{int(max(0.0, shed))}"
                               f" fanout={bus.get('fanout_kbps')}kbps")}
    return best


def _hot_region(rollup: dict, direction: str) -> Optional[dict]:
    """The driving region: under pressure, the one with the most
    stuck handoffs / the hottest task rate; when shrinking, the
    coldest one (the merge candidate)."""
    fed = rollup.get("federation")
    per = (fed or {}).get("per_region") or {}
    if not per:
        return None
    def load(r):
        return (_counter(r, "pending_handoffs") * 1000.0
                + _counter(r, "tasks_per_s"))
    pick = (max if direction == "up" else min)(
        per.items(), key=lambda kv: load(kv[1]))
    rname, r = pick
    return {"kind": "region", "id": rname, "peer": r.get("peer"),
            "detail": (f"tasks/s={r.get('tasks_per_s')}"
                       f" pending={r.get('pending_handoffs')}"
                       f" sent/acked={r.get('handoffs_sent')}"
                       f"/{r.get('handoffs_acked')}")}


def _hot_tenant(rollup: dict) -> Optional[dict]:
    """A tenant implicated by the audit plane: the namespace of the
    newest active divergence (the only per-tenant evidence the rollup
    carries today)."""
    audit = rollup.get("audit") or {}
    for d in reversed(audit.get("active") or []):
        if d.get("ns"):
            return {"kind": "tenant", "id": d["ns"],
                    "peer": d.get("peer_a"),
                    "detail": f"audit [{d.get('class')}]: "
                              f"{d.get('detail')}"}
    return None


def _hot_peer(rollup: dict, prev: Optional[dict]) -> Optional[dict]:
    """Per-peer fallback: the manager with the largest open backlog
    growth, else the worst tick p95, else a stale peer."""
    prev_peers = (prev or {}).get("peers") or {}
    best, best_backlog = None, 0.0
    worst_tick, worst_p95 = None, 0.0
    stale = None
    for peer, p in (rollup.get("peers") or {}).items():
        mt = p.get("mgr_tasks")
        if mt:
            # open work = queued (capacity-gated, not yet assigned)
            # plus in-flight (dispatched but not completed)
            backlog = (_counter(mt, "pending")
                       + _counter(mt, "dispatched")
                       - _counter(mt, "completed"))
            pmt = (prev_peers.get(peer) or {}).get("mgr_tasks")
            growth = backlog - (_counter(pmt, "pending")
                                + _counter(pmt, "dispatched")
                                - _counter(pmt, "completed"))
            score = max(growth, 0.0) * 1000.0 + backlog
            if score > best_backlog and backlog > 0:
                best_backlog = score
                best = {"kind": "peer", "id": peer, "peer": peer,
                        "proc": p.get("proc"),
                        "detail": f"backlog={int(backlog)} open task(s)"
                                  f" (+{int(max(growth, 0.0))})"}
        t = p.get("tick")
        if t and (t.get("p95_ms") or 0) > worst_p95:
            worst_p95 = t["p95_ms"]
            worst_tick = {"kind": "peer", "id": peer, "peer": peer,
                          "proc": p.get("proc"),
                          "detail": f"tick p95={t['p95_ms']}ms"
                                    f" over={t.get('over_budget')}"}
        if p.get("stale") and stale is None:
            stale = {"kind": "peer", "id": peer, "peer": peer,
                     "proc": p.get("proc"),
                     "detail": f"stale {p.get('age_s')}s"}
    return best or worst_tick or stale


_ACTUATOR = {
    ("bus_shard", "up"): "spawn_shard",
    ("bus_shard", "down"): "kill_shard",
    ("region", "up"): "split_region",
    ("region", "down"): "merge_regions",
    ("tenant", "up"): "evict_tenant",
    ("tenant", "down"): "evict_tenant",
}


def attribute(rollup: Optional[dict], prev: Optional[dict],
              slo_entry: dict, verdict: dict
              ) -> Tuple[Optional[dict], Optional[dict]]:
    """``(attribution, recommendation)`` for one alerting SLO.

    The breached signal routes the search — a ``bus.*`` signal looks at
    shards first, a ``fed.*`` signal at regions — then the fallback
    chain walks shard → region → tenant → peer until a section yields a
    driver.  Direction: a ``max`` breach is rising pressure ("up"); a
    ``min`` breach is "up" too when the fleet holds a backlog (it
    cannot keep up), and "down" only when the fleet is genuinely idle
    (the scale-in signal)."""
    threshold = verdict.get("threshold") or {}
    fleet = (rollup or {}).get("fleet") or {}
    backlog = ((fleet.get("tasks_pending") or 0)
               + (fleet.get("tasks_dispatched") or 0)
               - (fleet.get("tasks_completed") or 0))
    if "max" in threshold and "min" not in threshold:
        direction = "up"
    else:
        direction = "up" if backlog > 0 else "down"
    sig = slo_entry.get("signal") or ""
    chain: List[Optional[dict]] = []
    if sig.startswith("bus."):
        chain.append(_hot_shard(rollup or {}, prev))
    if sig.startswith("fed."):
        chain.append(_hot_region(rollup or {}, direction))
    chain += [_hot_shard(rollup or {}, prev) if sig.startswith("bus.")
              else None,
              _hot_region(rollup or {}, direction),
              _hot_tenant(rollup or {}),
              _hot_peer(rollup or {}, prev)]
    att = next((c for c in chain if c), None)
    if att is None:
        return None, {"direction": direction, "actuator": "shed_load",
                      "target": "fleet"}
    actuator = _ACTUATOR.get((att["kind"], direction), "shed_load")
    return att, {"direction": direction, "actuator": actuator,
                 "target": att["id"]}


# ---------------------------------------------------------------------------
# the engine: burn windows + episodes + forecasts over the ring
# ---------------------------------------------------------------------------

class _SloState:
    __slots__ = ("window", "forecaster", "streak", "mark", "confirmed",
                 "forecast_active")

    def __init__(self, slow: int, forecaster: SlopeForecaster):
        # (seq, breached) per sample; maxlen = the slow window
        self.window: Deque[Tuple[int, bool]] = collections.deque(
            maxlen=slow)
        self.forecaster = forecaster
        self.streak = 0
        self.mark = None  # fresh-evidence mark (auditor idiom)
        self.confirmed = False
        self.forecast_active = False


class HealthEngine:
    """The evaluation core: feed :meth:`observe` one fleet rollup per
    beat; it samples the signals into the ring, judges every SLO through
    the shared obs/slo.py core, advances burn windows / forecasters /
    episodes, and returns the newly emitted ``alert1`` records."""

    def __init__(self, spec=None, ring: Optional[HealthRing] = None,
                 interval: Optional[float] = None,
                 fast: int = FAST_WINDOW, slow: int = SLOW_WINDOW,
                 confirm: int = CONFIRM_STREAK,
                 horizon_s: float = FORECAST_HORIZON_S,
                 min_confidence: float = FORECAST_CONFIDENCE):
        self.spec = _slo.load_spec(spec)
        self.ring = ring or HealthRing()
        self.interval_s = interval_s() if interval is None else interval
        self.fast = max(1, fast)
        self.slow = max(self.fast, slow)
        self.confirm = max(1, confirm)
        self.horizon_s = horizon_s
        self.min_confidence = min_confidence
        self.seq = 0
        self.alerts: List[dict] = []  # emitted history (bounded)
        self._states: Dict[str, _SloState] = {}
        self._prev_rollup: Optional[dict] = None

    def _state(self, name: str) -> _SloState:
        st = self._states.get(name)
        if st is None:
            st = self._states[name] = _SloState(
                self.slow,
                SlopeForecaster(horizon_s=self.horizon_s,
                                min_confidence=self.min_confidence))
        return st

    def burn(self, name: str) -> Dict[str, float]:
        """Fast/slow-window burn rates (breaching sample fraction) for
        one SLO — 0.0 when no samples landed yet."""
        st = self._state(name)
        samples = list(st.window)
        fast = samples[-self.fast:]
        def frac(xs):
            return (sum(1 for _, b in xs if b) / len(xs)) if xs else 0.0
        return {"fast": round(frac(fast), 3),
                "slow": round(frac(samples), 3),
                "fast_window": self.fast, "slow_window": self.slow}

    def _mk_alert(self, now_ms: int, slo_entry: dict, v: dict,
                  kind: str, state: str, severity: str,
                  rollup: Optional[dict],
                  forecast: Optional[dict] = None) -> dict:
        alert = {
            "type": "alert1", "version": ALERT_VERSION,
            "ts_ms": now_ms, "seq": self.seq,
            "name": slo_entry["name"], "signal": slo_entry["signal"],
            "kind": kind, "state": state, "severity": severity,
            "observed": v.get("observed"),
            "threshold": v.get("threshold"),
            "burn": self.burn(slo_entry["name"]),
        }
        if forecast is not None:
            fc = dict(forecast)
            # forecast lead in evaluation intervals: the acceptance
            # number ("fires >= 2 intervals before the hard breach")
            fc["eta_intervals"] = round(fc["eta_s"]
                                        / max(self.interval_s, 1e-9), 1)
            alert["forecast"] = fc
        att, reco = attribute(rollup, self._prev_rollup, slo_entry, v)
        if att is not None:
            alert["attribution"] = att
        alert["recommendation"] = reco
        return validate_alert(alert)

    def observe(self, rollup: dict, now_ms: Optional[int] = None,
                signals: Optional[dict] = None) -> List[dict]:
        """One evaluation beat.  ``signals`` overrides the rollup
        flattening (the smoke threads window-exact values through).
        Returns newly emitted alert1 records, in emit order."""
        now_ms = _now_ms() if now_ms is None else now_ms
        if signals is None:
            signals = _slo.signals_from_rollup(rollup or {})
        self.seq += 1
        verdicts = [_slo.evaluate_one(s, signals)
                    for s in self.spec["slos"]]
        self.ring.append({
            "version": HEALTH_VERSION, "ts_ms": now_ms, "seq": self.seq,
            "interval_s": self.interval_s, "signals": signals,
            "failed": [v["name"] for v in verdicts
                       if v["status"] == "fail"],
            "unknown": [v["name"] for v in verdicts
                        if v["status"] == "unknown"],
        })
        # fresh-evidence mark: a stalled fleet keeps serving the same
        # rollup — streaks and forecasters must only advance on new
        # beacons, or a wedged window would "sustain" itself into a page
        mark = (rollup or {}).get("beacons_ingested")
        out: List[dict] = []
        for slo_entry, v in zip(self.spec["slos"], verdicts):
            st = self._state(slo_entry["name"])
            fresh = mark is None or mark != st.mark
            st.mark = mark
            if not fresh:
                continue
            breached = v["status"] == "fail"
            st.window.append((self.seq, breached))
            if v["status"] != "unknown":
                st.forecaster.observe(now_ms / 1000.0,
                                      float(v["observed"]))
            burn = self.burn(slo_entry["name"])
            # confirm: the whole fast window burns, sustained for the
            # confirm streak — one transient sample never alerts
            fast_full = (len(st.window) >= self.fast
                         and burn["fast"] >= 1.0)
            if fast_full:
                st.streak += 1
            elif not st.confirmed:
                st.streak = 0
            if fast_full and not st.confirmed \
                    and st.streak >= self.confirm:
                st.confirmed = True
                st.forecast_active = False
                out.append(self._mk_alert(
                    now_ms, slo_entry, v, "breach", "confirmed",
                    SEVERITY_PAGE, rollup))
            elif st.confirmed and burn["slow"] <= 0.0:
                # heal only once the SLOW window is clean (de-flap),
                # then re-arm: a new episode re-confirms + re-records
                st.confirmed = False
                st.streak = 0
                out.append(self._mk_alert(
                    now_ms, slo_entry, v, "breach", "healed",
                    SEVERITY_PAGE, rollup))
            if not st.confirmed and not breached:
                fc = None
                threshold = v.get("threshold") or {}
                if "max" in threshold:
                    fc = st.forecaster.forecast(threshold["max"], "max")
                if fc is None and "min" in threshold:
                    fc = st.forecaster.forecast(threshold["min"], "min")
                if fc is not None and not st.forecast_active:
                    st.forecast_active = True
                    out.append(self._mk_alert(
                        now_ms, slo_entry, v, "forecast", "confirmed",
                        SEVERITY_WARN, rollup, forecast=fc))
                elif fc is None:
                    st.forecast_active = False
        self._prev_rollup = rollup
        self.alerts.extend(out)
        del self.alerts[:-256]
        return out

    def active(self) -> List[dict]:
        """Confirmed, un-healed breach episodes — newest record per SLO
        (the auditor's ``active()`` shape, for the rollup/fleet_top)."""
        newest: Dict[str, dict] = {}
        for a in self.alerts:
            if a["kind"] != "breach":
                continue
            if a["state"] == "confirmed":
                newest[a["name"]] = a
            else:
                newest.pop(a["name"], None)
        return [a for name, a in newest.items()
                if self._states.get(name) and self._states[name].confirmed]

    def status(self) -> dict:
        return {
            "seq": self.seq,
            "interval_s": self.interval_s,
            "spec": self.spec.get("name"),
            "alerts": len(self.alerts),
            "active": self.active(),
            "last": self.alerts[-1] if self.alerts else None,
        }


# ---------------------------------------------------------------------------
# the watcher: aggregator + engine behind a bus client (healthd's body)
# ---------------------------------------------------------------------------

class HealthWatcher:
    """Embeds a :class:`FleetAggregator` (which embeds the AuditJoiner)
    and runs the engine on the beacon cadence.  The standalone
    ``healthd`` runner and scripts/health_smoke.py both drive THIS, so
    the smoke proves the daemon's actual path.

    ``capture_dump`` is the flight-ring pull used by the auto-capture
    path: the default publishes bus ``flight_dump`` requests (the
    auditor's idiom); an in-process harness passes its own dumper."""

    def __init__(self, bus=None, engine: Optional[HealthEngine] = None,
                 record_dir: Optional[str] = None,
                 publish: bool = True,
                 capture_dump: Optional[Callable[[], None]] = None,
                 on_alert: Optional[Callable[[dict], None]] = None):
        self.bus = bus
        self.engine = engine or HealthEngine()
        self.record_dir = str(record_dir) if record_dir else None
        self.publish = publish and bus is not None
        self.on_alert = on_alert
        self._capture_dump = capture_dump
        self._cap_at = 0.0
        self._last_beat = 0.0
        self._last_audit_eval = 0.0
        self.alerts_path = None
        if self.record_dir:
            os.makedirs(self.record_dir, exist_ok=True)
            self.alerts_path = os.path.join(self.record_dir,
                                            "healthd.alerts.jsonl")
        from p2p_distributed_tswap_tpu.obs.fleet_aggregator import (
            FleetAggregator)
        self.agg = FleetAggregator()
        if bus is not None:
            from p2p_distributed_tswap_tpu.obs import audit as _audit
            from p2p_distributed_tswap_tpu.obs.beacon import METRICS_TOPIC
            from p2p_distributed_tswap_tpu.runtime import ha as _ha
            bus.subscribe(METRICS_TOPIC)
            if _audit.enabled():
                bus.subscribe(_audit.AUDIT_TOPIC, raw=True)
            if _ha.enabled():
                bus.subscribe(_ha.HA_TOPIC, raw=True)

    # -- the auto-capture path (auditor idiom, ISSUE 11) ------------------
    def _maybe_capture(self, alert: dict) -> None:
        flight_dir = self.record_dir or os.environ.get("JG_FLIGHT_DIR")
        if not flight_dir:
            return
        now = time.monotonic()
        if now - self._cap_at < 30.0:
            return
        self._cap_at = now
        if self._capture_dump is not None:
            self._capture_dump()
        elif self.bus is not None:
            self.bus.publish("mapd", {"type": "flight_dump"}, raw=True)
            self.bus.publish("solver", {"type": "flight_dump"}, raw=True)
            time.sleep(1.2)  # flight dumps need a beat to land
        from p2p_distributed_tswap_tpu.obs import capture as _capture
        try:
            doc = _capture.from_flight_dir(flight_dir,
                                           source="auto_health")
            path = _capture.save(
                os.path.join(flight_dir, "healthd.capture.json"), doc)
            alert["capture"] = str(path)
        except (_capture.CaptureError, OSError) as e:
            alert["capture_error"] = str(e)

    def _emit(self, alert: dict) -> None:
        # capture FIRST: it enriches the record, and both the published
        # frame and the persisted jsonl line must carry the pointer
        if alert["severity"] == SEVERITY_PAGE \
                and alert["state"] == "confirmed" \
                and alert["kind"] == "breach":
            self._maybe_capture(alert)
        if self.publish:
            self.bus.publish(ALERT_TOPIC, alert, raw=True)
        if self.alerts_path:
            try:
                with open(self.alerts_path, "a") as f:
                    f.write(json.dumps(alert) + "\n")
            except OSError:
                pass
        if self.on_alert is not None:
            try:
                self.on_alert(alert)
            except Exception:
                pass  # a side-channel must never lose the alert itself

    def beat(self, now_ms: Optional[int] = None) -> List[dict]:
        """One evaluation beat: rollup → engine → emit.  Also publishes
        a ``health_beacon`` heartbeat so fleet_top can render the
        watcher's liveness even on a quiet fleet."""
        rollup = self.agg.rollup(now_ms)
        alerts = self.engine.observe(rollup, now_ms=now_ms)
        for a in alerts:
            self._emit(a)
        if self.publish:
            st = self.engine.status()
            self.bus.publish(ALERT_TOPIC, {
                "type": "health_beacon",
                "peer_id": getattr(self.bus, "peer_id", "healthd"),
                "ts_ms": _now_ms() if now_ms is None else now_ms,
                "seq": st["seq"],
                "interval_s": self.engine.interval_s,
                "spec": st["spec"],
                "active": len(st["active"]),
                "alerts": st["alerts"],
            }, raw=True)
        return alerts

    def pump(self, seconds: float) -> List[dict]:
        """Drive the watcher for ``seconds``: ingest beacons, judge the
        embedded auditor mid-window (fleet_top idiom — confirm streaks
        need repeated fresh-evidence rounds), beat on the interval."""
        out: List[dict] = []
        end = time.monotonic() + seconds
        while True:
            remaining = end - time.monotonic()
            if remaining <= 0:
                return out
            if self.bus is not None:
                f = self.bus.recv(timeout=min(0.25, remaining))
                if f and f.get("op") == "msg":
                    self.agg.ingest(f.get("data") or {})
            else:
                time.sleep(min(0.05, remaining))
            now = time.monotonic()
            if self.agg.audit.beacons \
                    and now - self._last_audit_eval > 0.5:
                self._last_audit_eval = now
                self.agg.audit.evaluate()
            if now - self._last_beat >= self.engine.interval_s:
                self._last_beat = now
                out.extend(self.beat())


def render_alert(a: dict) -> str:
    """One operator line per alert (the healthd stdout / smoke shape)."""
    mark = "🔴" if a["severity"] == SEVERITY_PAGE else "🟡"
    if a["state"] == "healed":
        mark = "🟢"
    line = (f"{mark} {a['severity'].upper()} {a['kind']} {a['state']} "
            f"[{a['name']}] {a['signal']}={a.get('observed')} "
            f"burn {a['burn']['fast']:g}/{a['burn']['slow']:g}")
    fc = a.get("forecast")
    if fc:
        line += (f" crosses in ~{fc['eta_s']:g}s "
                 f"({fc['eta_intervals']:g} intervals, "
                 f"conf {fc['confidence']:g})")
    att = a.get("attribution")
    if att:
        line += f" ← {att['kind']} {att['id']} ({att['detail']})"
    reco = a.get("recommendation")
    if reco:
        line += f" ⇒ {reco['actuator']}({reco['target']})"
    if a.get("capture"):
        line += f" 📼 {a['capture']}"
    return line


# ---------------------------------------------------------------------------
# the healthd runner
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    import sys

    from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient

    ap = argparse.ArgumentParser(
        description="continuous fleet health watcher (mapd.alert)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7400)
    ap.add_argument("--interval", type=float, default=None,
                    help="evaluation beat seconds (default: "
                         f"$JG_HEALTH_INTERVAL_S or {HEALTH_INTERVAL_S})")
    ap.add_argument("--spec", default=None,
                    help="SLO spec JSON (default: built-in rated-load)")
    ap.add_argument("--record", default=None, metavar="DIR",
                    help="append alert1 records to DIR/healthd.alerts."
                         "jsonl, persist the health1 ring to "
                         "DIR/healthd.ring.jsonl, and dump auto-"
                         "captures next to them")
    ap.add_argument("--for", dest="duration", type=float, default=0.0,
                    help="run for N seconds then exit (0 = forever); "
                         "exit 1 if any page fired, 2 if no beacons")
    ap.add_argument("--json", action="store_true",
                    help="print the final status as JSON (with --for)")
    args = ap.parse_args(argv)

    # launching the daemon IS the opt-in — arm the plane in-process so
    # the embedded helpers (and any child we spawn) agree it is on
    os.environ.setdefault(KILL_ENV, "1")
    ring = None
    if args.record:
        os.makedirs(args.record, exist_ok=True)
        ring = HealthRing(os.path.join(args.record,
                                       "healthd.ring.jsonl"))
    engine = HealthEngine(spec=args.spec, ring=ring,
                          interval=args.interval)
    try:
        bus = BusClient(host=args.host, port=args.port,
                        peer_id="healthd",
                        reconnect=args.duration <= 0)
    except OSError as e:
        print(f"healthd: cannot reach bus at {args.host}:{args.port} "
              f"({e})", file=sys.stderr)
        return 2
    watcher = HealthWatcher(
        bus, engine, record_dir=args.record,
        on_alert=lambda a: print(render_alert(a), flush=True))

    pages = 0

    def count_pages(alerts):
        nonlocal pages
        pages += sum(1 for a in alerts
                     if a["severity"] == SEVERITY_PAGE
                     and a["state"] == "confirmed")

    try:
        if args.duration > 0:
            count_pages(watcher.pump(args.duration))
            st = engine.status()
            if args.json:
                print(json.dumps(st, indent=2))
            else:
                print(f"HEALTH spec={st['spec']} seq={st['seq']} "
                      f"alerts={st['alerts']} "
                      f"active={len(st['active'])}")
            if watcher.agg.beacons_ingested == 0:
                return 2
            return 1 if pages else 0
        while True:
            count_pages(watcher.pump(10.0))
            st = engine.status()
            print(f"HEALTH spec={st['spec']} seq={st['seq']} "
                  f"alerts={st['alerts']} active={len(st['active'])}",
                  flush=True)
    except KeyboardInterrupt:
        return 0
    finally:
        bus.close()


if __name__ == "__main__":
    import sys
    sys.exit(main())
