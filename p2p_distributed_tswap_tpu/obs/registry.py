"""Unified live-metrics registry: the single backend for runtime counters.

Before this module, live counters were scattered across three ad-hoc
mechanisms — ``BusClient.net`` (NetworkMetrics), the tracer's counters dict
(obs/trace.py), and the heartbeat's budget counters (obs/heartbeat.py) —
with no single source of truth and no way to ask "how is the fleet doing
right now".  This registry is that source: counters, gauges, and
fixed-bucket histograms with label support, thread-safe, ALWAYS ON (an
increment is one dict op under a lock — no clock read, no allocation on the
hot path), and consumed by every read side:

- ``obs.trace`` counters/gauges delegate here (trace *spans* stay gated by
  JG_TRACE; the counters are live metrics and cost nothing to keep);
- solverd's SIGUSR1 / bus ``stats_request`` dumps snapshot it;
- the periodic ``mapd.metrics`` beacon (obs/beacon.py) publishes
  :meth:`Registry.snapshot` for manager-side aggregation
  (obs/fleet_aggregator.py) and the ``analysis/fleet_top.py`` view;
- :meth:`Registry.expose_text` renders the Prometheus text format, served
  on a tiny per-process HTTP endpoint when ``JG_METRICS_PORT`` is set
  (:func:`maybe_serve_http`).

Series are keyed by a flat Prometheus-style string — ``name`` or
``name{k="v",...}`` with labels sorted — so snapshots stay JSON-compact and
the C++ mirror (cpp/common/metrics.hpp MetricsRegistry) can emit the exact
same schema.  Metric names may contain dots (the tracer's historical
``bus.msgs_sent`` style); they are sanitized to underscores only at
Prometheus exposition time.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Iterable, Optional, Tuple

# Default histogram bounds in milliseconds, chosen so the 500 ms planning
# budget sits on a bucket edge (over/under budget is exact, not
# interpolated).  The +Inf bucket is implicit.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000)


def format_key(name: str, labels: Optional[dict] = None) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`format_key` (labels with quoted simple values)."""
    brace = key.find("{")
    if brace < 0:
        return key, {}
    name = key[:brace]
    labels: Dict[str, str] = {}
    for part in key[brace + 1:].rstrip("}").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k] = v.strip('"')
    return name, labels


class _Hist:
    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for b in self.bounds:
            if value <= b:
                break
            i += 1
        self.counts[i] += 1
        self.sum += value
        self.count += 1

    def to_dict(self) -> dict:
        return {"buckets": list(self.bounds), "counts": list(self.counts),
                "sum": round(self.sum, 6), "count": self.count}


def hist_quantile(hist: dict, q: float) -> Optional[float]:
    """Quantile estimate from a snapshot histogram dict (linear
    interpolation inside the winning bucket; the +Inf bucket reports its
    lower bound — an honest floor, not a fabricated value)."""
    count = hist.get("count", 0)
    if not count:
        return None
    bounds = hist["buckets"]
    counts = hist["counts"]
    rank = q * count
    seen = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        if seen + c >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            if i >= len(bounds):  # +Inf bucket
                return float(bounds[-1]) if bounds else None
            hi = bounds[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        seen += c
    return float(bounds[-1]) if bounds else None


def _prom_name(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isalpha() or ch in "_:" or (ch.isdigit() and i > 0)
        out.append(ch if ok else "_")
    return "".join(out)


class Registry:
    """Thread-safe counters / gauges / fixed-bucket histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, _Hist] = {}
        self._mono0 = time.monotonic()

    # -- write side -------------------------------------------------------
    def count(self, name: str, n: float = 1, **labels) -> None:
        key = format_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def gauge(self, name: str, value: float, **labels) -> None:
        key = format_key(name, labels)
        with self._lock:
            self._gauges[key] = value

    def observe(self, name: str, value: float,
                buckets: Optional[Iterable[float]] = None, **labels) -> None:
        key = format_key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist(
                    tuple(buckets) if buckets else DEFAULT_MS_BUCKETS)
            h.observe(value)

    def clear(self) -> None:
        """Drop every series (process entry / test isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._mono0 = time.monotonic()

    # -- read side --------------------------------------------------------
    def uptime_s(self) -> float:
        return time.monotonic() - self._mono0

    def counter_value(self, name: str, **labels) -> float:
        """Sum of every series of ``name`` whose labels include ``labels``
        (no labels: sum across all series of the name)."""
        total = 0.0
        with self._lock:
            items = list(self._counters.items())
        for key, v in items:
            n, ls = parse_key(key)
            if n == name and all(ls.get(k) == str(w)
                                 for k, w in labels.items()):
                total += v
        return total

    def gauge_value(self, name: str, default: Optional[float] = None,
                    **labels) -> Optional[float]:
        key = format_key(name, labels)
        with self._lock:
            return self._gauges.get(key, default)

    def snapshot(self) -> dict:
        """Point-in-time JSON-ready view (the beacon payload body; same
        schema as the C++ mirror's snapshot_json)."""
        with self._lock:
            return {
                "uptime_s": round(self.uptime_s(), 3),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "hists": {k: h.to_dict() for k, h in self._hists.items()},
            }

    def counters_flat(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def gauges_flat(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def network_summary(self) -> dict:
        """Bus accounting rollup (bus_client records here): message/byte
        totals plus uptime-averaged rates, the live equivalent of the
        reference's NetworkMetrics print."""
        e = self.uptime_s()
        sent_b = self.counter_value("bus.bytes_sent")
        recv_b = self.counter_value("bus.bytes_received")
        return {
            "messages_sent": int(self.counter_value("bus.msgs_sent")),
            "messages_received": int(self.counter_value("bus.msgs_received")),
            "bytes_sent": int(sent_b),
            "bytes_received": int(recv_b),
            "elapsed_s": round(e, 3),
            "send_kbps": round(sent_b * 8.0 / (e * 1000.0), 3) if e else 0.0,
            "recv_kbps": round(recv_b * 8.0 / (e * 1000.0), 3) if e else 0.0,
        }

    def expose_text(self) -> str:
        """Prometheus text exposition format (/metrics)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: h.to_dict() for k, h in self._hists.items()}
        lines = []
        typed: set = set()

        def emit(key: str, value, kind: str, suffix: str = "",
                 extra_label: str = "") -> None:
            name, labels = parse_key(key)
            pname = _prom_name(name)
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {kind}")
            pairs = [f'{k}="{v}"' for k, v in sorted(labels.items())]
            if extra_label:
                pairs.append(extra_label)
            lab = "{" + ",".join(pairs) + "}" if pairs else ""
            v = int(value) if float(value).is_integer() else value
            lines.append(f"{pname}{suffix}{lab} {v}")

        for key in sorted(counters):
            emit(key, counters[key], "counter")
        for key in sorted(gauges):
            emit(key, gauges[key], "gauge")
        for key in sorted(hists):
            h = hists[key]
            cum = 0
            for bound, c in zip(h["buckets"], h["counts"]):
                cum += c
                emit(key, cum, "histogram", "_bucket", f'le="{bound:g}"')
            emit(key, h["count"], "histogram", "_bucket", 'le="+Inf"')
            emit(key, h["sum"], "histogram", "_sum")
            emit(key, h["count"], "histogram", "_count")
        return "\n".join(lines) + "\n"


# -- module-level singleton (the process registry) -------------------------

_registry = Registry()


def get_registry() -> Registry:
    return _registry


def count(name: str, n: float = 1, **labels) -> None:
    _registry.count(name, n, **labels)


def gauge(name: str, value: float, **labels) -> None:
    _registry.gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    _registry.observe(name, value, **labels)


def snapshot() -> dict:
    return _registry.snapshot()


def expose_text() -> str:
    return _registry.expose_text()


# -- optional per-process HTTP /metrics endpoint ---------------------------

def serve_http(port: int, registry: Optional[Registry] = None,
               host: str = "127.0.0.1"):
    """Start a daemon-thread HTTP server exposing ``/metrics`` (Prometheus
    text) and ``/metrics.json`` (the beacon snapshot).  Returns the server
    (its ``server_port`` reports the bound port — pass 0 for ephemeral)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    reg = registry or _registry

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path.startswith("/metrics.json"):
                body = json.dumps(reg.snapshot()).encode()
                ctype = "application/json"
            elif self.path.startswith("/metrics") or self.path == "/":
                body = reg.expose_text().encode()
                ctype = "text/plain; version=0.0.4"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # scrape noise stays out of stdout
            pass

    srv = HTTPServer((host, port), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True,
                     name="jg-metrics-http").start()
    return srv


def maybe_serve_http(registry: Optional[Registry] = None):
    """Start the /metrics endpoint iff ``JG_METRICS_PORT`` is set (0 =
    ephemeral port).  Returns the server or None; a bind failure warns and
    returns None — metrics must never take a daemon down."""
    port = os.environ.get("JG_METRICS_PORT", "")
    if port == "":
        return None
    try:
        return serve_http(int(port), registry)
    except (OSError, ValueError) as e:
        import sys
        print(f"⚠️ metrics endpoint disabled (JG_METRICS_PORT={port}: {e})",
              file=sys.stderr)
        return None
