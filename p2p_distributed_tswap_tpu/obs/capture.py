"""Deterministic traffic capture (ISSUE 11): the ``capture1`` artifact.

The reference paper's experiments are statistical — every run re-samples
tasks and timing — so a tail breach or a state divergence found once
cannot be reproduced on demand.  This module fixes the *record* half of
record/replay: a versioned, self-contained description of one live
window's traffic, assembled from evidence the fleet already produces
(the sim pool's wire view, lifecycle event logs, flight-recorder rings):

- **fleet**: the deterministic run configuration — agent count, map
  side, the pool seed (initial agent placement is a pure function of
  it), bus shard count, solver, planning tick, and the manager's
  ``--seed`` (the satellite that threads one seed through every
  stochastic path fleetsim touches);
- **tasks**: every task the window dispatched — id, arrival offset from
  the capture epoch (ms), pickup and delivery cells.  Replay re-injects
  them open-loop at the same offsets with the same ids (the manager's
  ``taskat`` command), so the LOAD is deterministic even though the
  planner's internal scheduling stays live;
- **world**: every accepted ``world_update`` — offset, epoch, the
  ``[x, y, blocked]`` toggle list — replayed as
  ``world_update_request`` frames at the same offsets;
- **baseline**: the original window's signals (tasks/s, phase
  percentiles) so a replay can state its fidelity drift.

Assembly paths (all produce the same schema):

1. live — ``analysis/fleetsim.py --capture out.json`` attaches a
   :class:`CaptureRecorder` to the run;
2. post-mortem — ``analysis/blackbox.py --capture out.json`` rebuilds
   the window from flight-recorder dumps (the pool emits ``capture.meta``
   / ``task.spec`` / ``world.update`` evidence events into the
   always-on ring exactly for this);
3. automatic — the standalone auditor dumps a capture next to the
   flight rings when it confirms a RED divergence, so a live incident
   arrives pre-packaged for replay.

The determinism CONTRACT replay proves (see scripts/chaos_gate.py and
ARCHITECTURE.md): two replays of one capture complete the identical
task-id set with zero duplicates and land equal audit ledger/view
digests at the final (drained) watermark; timeline phase stats land
within a stated tolerance of the baseline.  Lane digests (positions)
are recorded for diagnosis but not asserted — assignment interleaving
is the live planner's, by design.

Schema versioning is STRICT: :func:`validate` rejects any document
whose ``version`` is not exactly ``capture1`` — a replay driven by a
half-understood future capture would fabricate a "reproduction".
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

CAPTURE_VERSION = "capture1"

# evidence event names (obs/events.py emissions the assembly paths scan)
EV_META = "capture.meta"
EV_TASK = "task.spec"
EV_WORLD = "world.update"

# fleet keys a capture must carry to be replayable at all; the rest
# (shards, solver, tick_ms, heartbeat_s, manager_seed) have defaults
_REQUIRED_FLEET = ("agents", "side", "seed")
_FLEET_DEFAULTS = {"shards": 1, "solver": "native", "tick_ms": 250,
                   "heartbeat_s": 2.0, "manager_seed": None}


class CaptureError(ValueError):
    """Malformed or wrong-version capture document."""


def _now_ms() -> int:
    return time.time_ns() // 1_000_000


def _check_cell(pt, side: int, what: str) -> List[int]:
    if (not isinstance(pt, (list, tuple)) or len(pt) != 2
            or not all(isinstance(v, int) for v in pt)):
        raise CaptureError(f"{what}: cell must be [x, y], got {pt!r}")
    x, y = pt
    if side and not (0 <= x < side and 0 <= y < side):
        raise CaptureError(f"{what}: cell {pt} outside {side}x{side} map")
    return [int(x), int(y)]


def validate(doc: dict) -> dict:
    """Validate (and normalize in place) a capture document.  Raises
    :class:`CaptureError` on anything replay could misinterpret —
    including any version other than ``capture1``: an unknown schema
    must be REJECTED, never half-replayed."""
    if not isinstance(doc, dict):
        raise CaptureError("capture must be a JSON object")
    version = doc.get("version")
    if version != CAPTURE_VERSION:
        raise CaptureError(
            f"unsupported capture version {version!r} "
            f"(this build replays {CAPTURE_VERSION!r} only)")
    fleet = doc.get("fleet")
    if not isinstance(fleet, dict):
        raise CaptureError("capture has no fleet section")
    for k in _REQUIRED_FLEET:
        if not isinstance(fleet.get(k), int):
            raise CaptureError(f"fleet.{k} missing or not an int")
    if fleet["agents"] <= 0 or fleet["side"] <= 1:
        raise CaptureError(
            f"fleet agents={fleet['agents']} side={fleet['side']} "
            "is not a runnable fleet")
    for k, dflt in _FLEET_DEFAULTS.items():
        fleet.setdefault(k, dflt)
    side = fleet["side"]
    tasks = doc.get("tasks")
    if not isinstance(tasks, list) or not tasks:
        raise CaptureError("capture has no tasks — nothing to replay")
    seen_ids = set()
    for i, t in enumerate(tasks):
        if not isinstance(t, dict):
            raise CaptureError(f"tasks[{i}] is not an object")
        for k in ("id", "t_ms"):
            if not isinstance(t.get(k), int):
                raise CaptureError(f"tasks[{i}].{k} missing or not an int")
        if t["id"] in seen_ids:
            raise CaptureError(f"duplicate task id {t['id']}")
        seen_ids.add(t["id"])
        t["pickup"] = _check_cell(t.get("pickup"), side,
                                  f"tasks[{i}].pickup")
        t["delivery"] = _check_cell(t.get("delivery"), side,
                                    f"tasks[{i}].delivery")
    tasks.sort(key=lambda t: (t["t_ms"], t["id"]))
    world = doc.setdefault("world", [])
    if not isinstance(world, list):
        raise CaptureError("world section must be a list")
    for i, w in enumerate(world):
        if not isinstance(w, dict) or not isinstance(w.get("t_ms"), int):
            raise CaptureError(f"world[{i}] needs an int t_ms")
        toggles = w.get("toggles")
        if not isinstance(toggles, list) or not toggles:
            raise CaptureError(f"world[{i}] has no toggles")
        for tg in toggles:
            # ints (or bools for the blocked flag); integral floats are
            # accepted too — the C++ wire's JSON numbers may land as
            # doubles.  Anything else must REJECT as CaptureError, never
            # escape as a bare TypeError (the exit-2 contract).
            if (not isinstance(tg, (list, tuple)) or len(tg) != 3
                    or not all(isinstance(v, (int, bool))
                               or (isinstance(v, float)
                                   and v.is_integer()) for v in tg)):
                raise CaptureError(
                    f"world[{i}] toggle must be [x, y, blocked] ints, "
                    f"got {tg!r}")
        w["toggles"] = [[int(a), int(b), 1 if c else 0]
                        for a, b, c in toggles]
        w.setdefault("seq", 0)
    world.sort(key=lambda w: w["t_ms"])
    if not isinstance(doc.get("duration_ms"), int):
        doc["duration_ms"] = max(
            [t["t_ms"] for t in tasks] + [w["t_ms"] for w in world])
    doc.setdefault("baseline", None)
    doc.setdefault("source", "unknown")
    doc.setdefault("created_ms", _now_ms())
    return doc


def save(path, doc: dict) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(validate(doc), indent=2) + "\n")
    return path


def load(path) -> dict:
    path = Path(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise CaptureError(f"cannot read capture {path}: {e}") from None
    return validate(doc)


def task_ids(doc: dict) -> List[int]:
    return sorted(t["id"] for t in doc["tasks"])


def schedule(doc: dict) -> List[Tuple[int, str, dict]]:
    """The merged replay schedule: ``(t_ms, kind, payload)`` sorted by
    offset — ``kind`` is ``task`` or ``world``.  Ties replay tasks
    first (a toggle recorded in the same millisecond as a dispatch was
    validated against a ledger that already held the task)."""
    events = [(t["t_ms"], "task", t) for t in doc["tasks"]]
    events += [(w["t_ms"], "world", w) for w in doc.get("world") or []]
    events.sort(key=lambda e: (e[0], 0 if e[1] == "task" else 1))
    return events


# ---------------------------------------------------------------------------
# live recorder — the fleetsim --capture hook
# ---------------------------------------------------------------------------

class CaptureRecorder:
    """Accumulate one window's traffic as it happens.

    The sim pool feeds :meth:`record_task` on every first-seen task and
    :meth:`record_world` on every accepted world update; the harness
    calls :meth:`finalize` with the window's measured baseline.  Offsets
    are measured from construction time (the capture epoch) — replay
    re-anchors at its own fleet-ready moment."""

    def __init__(self, fleet: Dict, t0: Optional[float] = None):
        self.fleet = dict(fleet)
        self.t0 = time.monotonic() if t0 is None else t0
        self.tasks: List[dict] = []
        self.world: List[dict] = []
        self._seen: set = set()

    def _off_ms(self, t: Optional[float]) -> int:
        return int(((time.monotonic() if t is None else t)
                    - self.t0) * 1000.0)

    def record_task(self, task_id: int, pickup, delivery,
                    t: Optional[float] = None) -> bool:
        """First sighting wins; re-dispatches of a known id are not new
        traffic (a withdrawn/re-queued task replays from its original
        arrival)."""
        tid = int(task_id)
        if tid in self._seen:
            return False
        self._seen.add(tid)
        self.tasks.append({"id": tid, "t_ms": self._off_ms(t),
                           "pickup": [int(pickup[0]), int(pickup[1])],
                           "delivery": [int(delivery[0]),
                                        int(delivery[1])]})
        return True

    def record_world(self, seq: int, toggles, t: Optional[float] = None
                     ) -> None:
        if not toggles:
            return
        self.world.append({"t_ms": self._off_ms(t), "seq": int(seq or 0),
                           "toggles": [[int(a), int(b), 1 if c else 0]
                                       for a, b, c in toggles]})

    def finalize(self, baseline: Optional[dict] = None,
                 source: str = "live") -> dict:
        doc = {
            "version": CAPTURE_VERSION,
            "created_ms": _now_ms(),
            "source": source,
            "fleet": dict(self.fleet),
            "tasks": list(self.tasks),
            "world": list(self.world),
            "duration_ms": self._off_ms(None),
            "baseline": baseline,
        }
        return validate(doc)


# ---------------------------------------------------------------------------
# event-sourced assembly — flight rings / event logs to capture1
# ---------------------------------------------------------------------------

def from_events(events: Iterable[dict],
                fleet_overrides: Optional[dict] = None,
                source: str = "flight") -> dict:
    """Assemble a capture from structured evidence events (flight-ring
    dumps or ``*.events.jsonl`` lines): ``capture.meta`` carries the
    fleet config, ``task.spec`` one task's endpoints, ``world.update``
    one accepted toggle batch.  Offsets re-anchor at the earliest
    ``capture.meta`` timestamp (fallback: the earliest evidence event).
    ``fleet_overrides`` fills or overrides config keys the rings did
    not carry.  Raises :class:`CaptureError` when no tasks (or no
    usable fleet config) survive — a capture that cannot replay must
    fail loudly at build time, not at replay time."""
    fleet: Dict = {}
    metas: List[dict] = []
    tasks: Dict[int, dict] = {}
    world: List[dict] = []
    world_seen: set = set()
    t_min: Optional[int] = None
    for ev in events:
        if not isinstance(ev, dict):
            continue
        name = ev.get("event")
        ts = ev.get("ts_ms")
        if name not in (EV_META, EV_TASK, EV_WORLD) \
                or not isinstance(ts, int):
            continue
        if name == EV_META:
            metas.append(ev)
            continue
        if t_min is None or ts < t_min:
            t_min = ts
        if name == EV_TASK:
            tid = ev.get("task_id")
            if isinstance(tid, int) and tid not in tasks \
                    and isinstance(ev.get("pickup"), list) \
                    and isinstance(ev.get("delivery"), list):
                tasks[tid] = {"id": tid, "ts_ms": ts,
                              "pickup": ev["pickup"],
                              "delivery": ev["delivery"]}
        elif name == EV_WORLD:
            seq = ev.get("seq") or 0
            # several pool/agent processes may witness the same
            # world_update broadcast: dedup on (seq, toggles)
            key = (seq, json.dumps(ev.get("toggles")))
            if key in world_seen or not ev.get("toggles"):
                continue
            world_seen.add(key)
            world.append({"ts_ms": ts, "seq": seq,
                          "toggles": ev["toggles"]})
    # fleet config: merge every meta (earliest first — the pool emits
    # side/agents/seed, the harness adds shards/solver/tick), overrides
    # last
    for ev in sorted(metas, key=lambda e: e.get("ts_ms", 0)):
        for k in ("agents", "side", "seed", "shards", "solver",
                  "tick_ms", "heartbeat_s", "manager_seed"):
            if ev.get(k) is not None:
                fleet[k] = ev[k]
    fleet.update(fleet_overrides or {})
    if not tasks:
        raise CaptureError(
            "no task.spec evidence found — nothing to replay (was the "
            "ring dumped after the window, or did it rotate past it?)")
    t0 = min((e.get("ts_ms") for e in metas
              if isinstance(e.get("ts_ms"), int)), default=None)
    if t0 is None or (t_min is not None and t0 > t_min):
        t0 = t_min
    doc = {
        "version": CAPTURE_VERSION,
        "created_ms": _now_ms(),
        "source": source,
        "fleet": fleet,
        "tasks": [{"id": t["id"], "t_ms": max(0, t["ts_ms"] - t0),
                   "pickup": t["pickup"], "delivery": t["delivery"]}
                  for t in tasks.values()],
        "world": [{"t_ms": max(0, w["ts_ms"] - t0), "seq": w["seq"],
                   "toggles": w["toggles"]} for w in world],
        "baseline": None,
    }
    return validate(doc)


def iter_evidence_files(directory) -> Iterable[dict]:
    """Yield structured events from every flight dump and event log in a
    directory (the same sources analysis/blackbox.py merges)."""
    directory = Path(directory)
    for pattern in ("*.flight.jsonl", "*.events.jsonl",
                    "trace/*.events.jsonl"):
        for path in sorted(directory.glob(pattern)):
            try:
                text = path.read_text(errors="ignore")
            except OSError:
                continue
            for line in text.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def from_flight_dir(directory, fleet_overrides: Optional[dict] = None,
                    source: str = "flight") -> dict:
    """Post-mortem capture: rebuild the window from the flight-recorder
    dumps (and any event logs) in ``directory``."""
    return from_events(iter_evidence_files(directory),
                       fleet_overrides=fleet_overrides, source=source)
