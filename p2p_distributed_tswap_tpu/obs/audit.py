"""Fleet audit plane (ISSUE 10): continuous state-consistency digests,
world-epoch tracking, and bus-driven divergence drill-down.

After PR 3 (device-resident fleet state), PR 8 (multi-tenant slabs) and
PR 9 (dynamic worlds) the SAME logical state lives in five places — the
manager's task ledger and packed-encoder shadow, solverd's device slab
and host mirrors, the per-goal field cache, and every (sim-)agent's
local task view — with nothing observing that they still agree.  This
module is that observer:

- **digest primitives** — FNV-1a-64 chains over canonically packed
  state tuples, mirrored byte-for-byte in ``cpp/common/audit.hpp``
  (golden-tested via ``codec_golden --audit-*`` like shardmap):
  :func:`lane_digest` (sorted active ``(lane, pos, goal)`` int32
  triples — the manager's encoder shadow and solverd's mirrors hash to
  the SAME value iff they hold the same fleet), :func:`ledger_digest`
  (sorted ``(task_id, state, pickup, delivery)`` tuples),
  :func:`view_digest` (sorted held task ids), :func:`cells_digest`
  (sorted fresh field-cache goal cells);
- **the audit beacon** — every stateful process publishes a compact
  ``audit1`` binary blob (list of ``(section, count, seq, epoch,
  digest)`` entries, base64 in an ``audit_beacon`` JSON frame) on bus
  topic ``mapd.audit`` every ~2 s.  ``seq`` is the packed plan-chain
  tick and ``epoch`` the monotone ``world_seq`` bumped by every
  ``world_update`` — the watermarks the auditor joins on.  The manager
  ships a RING of its last few per-tick shadow digests so the join
  lands despite beacon-cadence skew.  Capability negotiation rides the
  beacon payload (``caps: ["audit1"]``): the driller only queries
  peers that advertised it;
- **the auditor** (:class:`AuditJoiner`) — joins digests at matching
  ``(seq, epoch)`` watermarks and classifies mismatches:
  ``roster`` (manager shadow vs solverd mirror at the same seq),
  ``device_mirror`` (solverd device pull vs its own host mirror),
  ``view`` (manager in-flight task set vs agent-pool held set, judged
  only when both sides are STABLE across beacons — task churn must not
  read as divergence; AMBER — dispatch/withdraw/done propagation
  windows make it a lead, not a page), ``stale_epoch`` /
  ``epoch_unaware`` (world
  epochs drifting apart; the PR 9 caveat — a namespaced manager
  defaulting dynamic-world OFF — surfaces here instead of in
  folklore), ``silent`` (a previously-beaconing peer gone quiet while
  the fleet advances).  Per-class streak thresholds confirm a
  divergence; confirmed records append to ``<dir>/auditor.audit.jsonl``
  (``analysis/blackbox.py --audit`` merges them into the black-box
  readout) and fire ``on_divergence`` (the standalone auditor publishes
  a bus ``flight_dump`` and turns the verdict RED);
- **the bisect driller** (:class:`AuditDriller`) — turns "digests
  differ" into "agent X's goal differs: manager says (88,12), solverd
  says (88,11)" WITHOUT shipping full state: ``audit_drill_request``
  frames ask both sides for range digests over lane halves, recursing
  into the first divergent half down to a leaf, where rows are fetched
  and diffed field-by-field.

``JG_AUDIT=0`` is the kill switch: no process publishes or subscribes
anything audit-related and the wire is byte-identical to the pre-audit
build (live pin test in tests/test_audit.py).  ``JG_AUDIT_TEST_HOOKS=1``
arms solverd's injected-corruption hook (``audit_corrupt`` frames) for
the CI drill (scripts/audit_smoke.py).

Standalone:
    python -m p2p_distributed_tswap_tpu.obs.audit --port 7400 \
        [--once --wait 6] [--json] [--drill] [--record DIR]
"""

from __future__ import annotations

import base64
import json
import os
import struct
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

AUDIT_TOPIC = "mapd.audit"
AUDIT_CAP = "audit1"
AUDIT_INTERVAL_S = 2.0
KILL_ENV = "JG_AUDIT"
HOOKS_ENV = "JG_AUDIT_TEST_HOOKS"
INTERVAL_ENV = "JG_AUDIT_INTERVAL_S"

# digest sections (mirrored in cpp/common/audit.hpp — never renumber)
SEC_SHADOW = 1   # manager: packed-encoder shadow (lane,pos,goal) @ seq
SEC_MIRROR = 2   # solverd: host mirror lanes @ last applied seq
SEC_DEVICE = 3   # solverd: device-pulled lanes @ the same seq
SEC_FIELDS = 4   # solverd: fresh (epoch-current) field-cache goal cells
SEC_LEDGER = 5   # manager: full task ledger (id,state,pickup,delivery)
SEC_VIEW = 6     # in-flight task-id set (manager side and agent side)

SECTION_NAMES = {SEC_SHADOW: "shadow", SEC_MIRROR: "mirror",
                 SEC_DEVICE: "device", SEC_FIELDS: "fields",
                 SEC_LEDGER: "ledger", SEC_VIEW: "view"}

# task-ledger state bytes (ledger_digest tuples)
TASK_PENDING = 0
TASK_TO_PICKUP = 1
TASK_TO_DELIVERY = 2


def enabled() -> bool:
    """The audit plane is ON unless JG_AUDIT=0 (the kill switch that
    keeps the wire byte-identical to the pre-audit build)."""
    return os.environ.get(KILL_ENV, "") != "0"


def hooks_enabled() -> bool:
    return os.environ.get(HOOKS_ENV, "") == "1"


def interval_s() -> float:
    try:
        return float(os.environ.get(INTERVAL_ENV, "") or AUDIT_INTERVAL_S)
    except ValueError:
        return AUDIT_INTERVAL_S


# ---------------------------------------------------------------------------
# digest primitives — byte-identical to cpp/common/audit.hpp
# ---------------------------------------------------------------------------

FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3
_U64 = (1 << 64) - 1


def fnv1a64(data: bytes, h: int = FNV64_OFFSET) -> int:
    """FNV-1a over ``data`` (64-bit), chainable via ``h``."""
    for b in data:
        h = ((h ^ b) * FNV64_PRIME) & _U64
    return h


def digest_hex(d: int) -> str:
    """Canonical 16-char lowercase hex — digests cross the JSON wire as
    strings (a u64 would round through the double-typed C++ Json)."""
    return f"{d & _U64:016x}"


def lane_digest(lanes, pos, goal) -> Tuple[int, int]:
    """``(digest, count)`` over active-lane triples, sorted by lane
    ascending, each packed as little-endian ``<iii``.  The manager's
    encoder shadow and solverd's host/device mirrors hash equal iff
    they hold the same (lane -> pos, goal) map."""
    import numpy as np

    lanes = np.asarray(lanes, np.int32)
    pos = np.asarray(pos, np.int32)
    goal = np.asarray(goal, np.int32)
    order = np.argsort(lanes, kind="stable")
    tri = np.column_stack([lanes[order], pos[order],
                           goal[order]]).astype("<i4")
    return fnv1a64(tri.tobytes()), int(lanes.size)


_LEDGER_TUPLE = struct.Struct("<qBii")


def ledger_digest(tasks) -> Tuple[int, int]:
    """``(digest, count)`` over ``(task_id, state, pickup_cell,
    delivery_cell)`` tuples sorted by (task_id, state), each packed
    little-endian ``<qBii`` (17 bytes)."""
    buf = bytearray()
    for tid, state, pickup, delivery in sorted(tasks):
        buf += _LEDGER_TUPLE.pack(int(tid), int(state) & 0xFF,
                                  int(pickup), int(delivery))
    return fnv1a64(bytes(buf)), len(buf) // _LEDGER_TUPLE.size


def view_digest(task_ids) -> Tuple[int, int]:
    """``(digest, count)`` over sorted held/in-flight task ids, each
    packed ``<q``."""
    ids = sorted(int(t) for t in task_ids)
    buf = b"".join(struct.pack("<q", t) for t in ids)
    return fnv1a64(buf), len(ids)


def cells_digest(cells) -> Tuple[int, int]:
    """``(digest, count)`` over sorted int32 cells (field-cache goals
    fresh at the current epoch), each packed ``<i``."""
    cs = sorted(int(c) for c in cells)
    buf = b"".join(struct.pack("<i", c) for c in cs)
    return fnv1a64(buf), len(cs)


# ---------------------------------------------------------------------------
# audit1 binary blob — the digest-beacon payload body
# ---------------------------------------------------------------------------

AUDIT_MAGIC = 0x31445541  # b"AUD1" little-endian
AUDIT_VERSION = 1
_AUD_HEAD = struct.Struct("<IBBH")   # magic, version, flags, n_entries
_AUD_ENTRY = struct.Struct("<BIqqQ")  # section, count, seq, epoch, digest


class AuditCodecError(ValueError):
    """Malformed audit1 blob (bad magic/version/length)."""


@dataclass(frozen=True)
class AuditEntry:
    """One digest record: ``seq`` is the plan-chain watermark, ``epoch``
    the world epoch (monotone ``world_seq``) the digest was computed
    under, ``digest`` the u64 FNV chain over ``count`` state tuples."""
    section: int
    count: int
    seq: int
    epoch: int
    digest: int


def encode_audit(entries: List[AuditEntry]) -> bytes:
    out = bytearray(_AUD_HEAD.pack(AUDIT_MAGIC, AUDIT_VERSION, 0,
                                   len(entries)))
    for e in entries:
        out += _AUD_ENTRY.pack(e.section & 0xFF, e.count, e.seq, e.epoch,
                               e.digest & _U64)
    return bytes(out)


def decode_audit(buf: bytes) -> List[AuditEntry]:
    if len(buf) < _AUD_HEAD.size:
        raise AuditCodecError("short audit1 blob")
    magic, version, _flags, n = _AUD_HEAD.unpack_from(buf, 0)
    if magic != AUDIT_MAGIC:
        raise AuditCodecError(f"bad audit1 magic 0x{magic:08x}")
    if version != AUDIT_VERSION:
        raise AuditCodecError(f"unsupported audit1 version {version}")
    need = _AUD_HEAD.size + n * _AUD_ENTRY.size
    if len(buf) != need:
        raise AuditCodecError(f"audit1 length {len(buf)} != {need}")
    out = []
    off = _AUD_HEAD.size
    for _ in range(n):
        sec, count, seq, epoch, digest = _AUD_ENTRY.unpack_from(buf, off)
        off += _AUD_ENTRY.size
        out.append(AuditEntry(sec, count, seq, epoch, digest))
    return out


def encode_audit_b64(entries: List[AuditEntry]) -> str:
    return base64.b64encode(encode_audit(entries)).decode()


def decode_audit_b64(data: str) -> List[AuditEntry]:
    try:
        raw = base64.b64decode(data, validate=True)
    except Exception as e:
        raise AuditCodecError(f"bad audit1 base64: {e}") from None
    return decode_audit(raw)


# ---------------------------------------------------------------------------
# beacon publisher (the audit analog of obs/beacon.py MetricsBeacon)
# ---------------------------------------------------------------------------

class AuditBeacon:
    """Tick-driven audit beacon: ``build`` returns ``(entries, extra)``
    where ``extra`` merges into the payload (buckets, epoch, dynamic
    flag...).  Publishes raw (un-namespaced) — the audit plane is
    operator/cross-tenant infrastructure like ``mapd.metrics``; a
    tenant-scoped emitter says so via the ``ns`` payload field."""

    def __init__(self, bus, proc: str,
                 build: Callable[[], Tuple[List[AuditEntry], dict]],
                 interval: Optional[float] = None, ns: str = ""):
        self.bus = bus
        self.proc = proc
        self.build = build
        self.interval_s = interval_s() if interval is None else interval
        self.ns = ns
        self.published = 0
        self._last = 0.0
        self._effective_interval = self.interval_s

    def payload(self) -> Optional[dict]:
        built = self.build()
        if built is None:
            return None
        entries, extra = built
        out = {
            "type": "audit_beacon",
            "peer_id": getattr(self.bus, "peer_id", self.proc),
            "proc": self.proc,
            "ns": self.ns,
            "pid": os.getpid(),
            "ts_ms": time.time_ns() // 1_000_000,
            # advertise the EFFECTIVE cadence (self-throttle included):
            # the joiner's silent threshold is 3x this value, so a big
            # fleet whose digest build stretches the beat must not keep
            # promising the configured interval or it reads as silent
            "interval_s": self._effective_interval,
            "caps": [AUDIT_CAP],
            "data": encode_audit_b64(entries),
        }
        out.update(extra or {})
        return out

    def maybe_beat(self, now: Optional[float] = None) -> Optional[dict]:
        now = time.monotonic() if now is None else now
        if self._last and now - self._last < self._effective_interval:
            return None
        self._last = now
        t0 = time.perf_counter()
        payload = self.payload()
        build_s = time.perf_counter() - t0
        # self-throttle: the digest body re-hashes the whole fleet, and
        # its cost grows with resident lanes (~3.5 µs/lane pure-python
        # FNV).  Cap the always-on overhead at ~2% of the host loop by
        # stretching the effective cadence when a build runs long — a
        # 10k-lane fleet beacons every ~3.5 s instead of stalling every
        # tick-loop iteration at the configured 2 s.
        self._effective_interval = max(self.interval_s, 50.0 * build_s)
        if payload is None:
            return None
        # re-stamp with THIS beat's effective cadence — the payload was
        # built before the throttle update, and a first long build must
        # not promise a beat it will not keep
        payload["interval_s"] = self._effective_interval
        self.bus.publish(AUDIT_TOPIC, payload, raw=True)
        self.published += 1
        return payload


# ---------------------------------------------------------------------------
# drill responder helpers (solverd / tests use these; the C++ manager
# mirrors the range rule natively)
# ---------------------------------------------------------------------------

DRILL_LEAF = 4  # ranges at or under this size answer with rows


def range_digest(lanes, pos, goal, lo: int, hi: int) -> Tuple[int, int]:
    """Digest over the active triples whose lane falls in [lo, hi)."""
    import numpy as np

    lanes = np.asarray(lanes, np.int64)
    sel = (lanes >= lo) & (lanes < hi)
    return lane_digest(np.asarray(lanes)[sel],
                       np.asarray(pos)[sel], np.asarray(goal)[sel])


def drill_answer(req: dict, lanes, pos, goal,
                 names: Optional[List[Optional[str]]] = None,
                 peer_id: str = "") -> dict:
    """Build the ``audit_drill_response`` for one request over an
    active-lane view (``lanes``/``pos``/``goal`` parallel arrays)."""
    import numpy as np

    lo = int(req.get("lo") or 0)
    hi = int(req.get("hi") or 0)
    d, n = range_digest(lanes, pos, goal, lo, hi)
    resp = {"type": "audit_drill_response",
            "req_id": req.get("req_id"),
            "peer_id": peer_id,
            "target": req.get("target"),
            "view": req.get("view"),
            "lo": lo, "hi": hi,
            "digest": digest_hex(d), "count": n}
    if req.get("rows") or hi - lo <= DRILL_LEAF:
        la = np.asarray(lanes, np.int64)
        sel = np.flatnonzero((la >= lo) & (la < hi))
        rows = []
        for k in sel:
            lane = int(la[k])
            name = ""
            if names is not None and 0 <= lane < len(names):
                name = names[lane] or ""
            rows.append([lane, int(np.asarray(pos)[k]),
                         int(np.asarray(goal)[k]), 1, name])
        resp["rows"] = sorted(rows)
    return resp


# ---------------------------------------------------------------------------
# the auditor: join digests at matching watermarks, classify divergence
# ---------------------------------------------------------------------------

# red = state provably forked at a shared watermark (or a peer died);
# amber = advisory — `view` compares the manager's ledger against
# agent-side beacons through multi-second propagation windows (task
# dispatch/withdraw/done all in flight), so a sustained mismatch is a
# lead to investigate, not a page; epoch drift likewise.
def flight_dump_trigger(bus, throttle_s: float = 30.0):
    """An ``on_divergence`` callable that pulls the fleet's black boxes
    (bus ``flight_dump`` on both the operator and solver planes) so the
    moments before a state fork survive for ``blackbox --audit`` — at
    most once per ``throttle_s`` episode window.  Shared by the
    standalone auditor CLI and fleet_top's live mode."""
    state = {"at": 0.0}

    def trigger(rec: dict) -> None:
        now = time.monotonic()
        if now - state["at"] > throttle_s:
            state["at"] = now
            bus.publish("mapd", {"type": "flight_dump"}, raw=True)
            bus.publish("solver", {"type": "flight_dump"}, raw=True)

    return trigger


RED_CLASSES = ("roster", "device_mirror", "silent")
AMBER_CLASSES = ("view", "stale_epoch", "epoch_unaware")
# evidence rounds (fresh-beacon evaluations) a mismatch must survive
# before it is CONFIRMED — even the exact-watermark joins require two,
# because a process restart can briefly overlay old-run and new-run
# seqs at the same watermark
CONFIRM_STREAK = {"roster": 2, "device_mirror": 2, "view": 3,
                  "silent": 2, "stale_epoch": 3, "epoch_unaware": 3}
RING_KEEP = 64  # per-peer per-section (seq -> entry) history bound


class _AuditPeer:
    __slots__ = ("proc", "ns", "last_ms", "interval_s", "beacons",
                 "rings", "latest", "stable", "epoch", "dynamic",
                 "buckets")

    def __init__(self):
        self.proc = "?"
        self.ns = ""
        self.last_ms = 0
        self.interval_s = AUDIT_INTERVAL_S
        self.beacons = 0
        # section -> {seq: AuditEntry} (insertion-ordered, bounded)
        self.rings: Dict[int, Dict[int, AuditEntry]] = {}
        self.latest: Dict[int, AuditEntry] = {}
        # section -> consecutive beacons with an unchanged digest (the
        # stability evidence fuzzy comparisons require)
        self.stable: Dict[int, int] = {}
        self.epoch = 0
        self.dynamic: Optional[bool] = None
        self.buckets: Optional[dict] = None


class AuditJoiner:
    """Merge ``audit_beacon`` payloads and judge fleet consistency.

    Feed :meth:`ingest` every bus frame data dict (non-beacons are
    ignored); call :meth:`evaluate` about once per beacon interval;
    read :meth:`status` for the rollup."""

    def __init__(self, record_path=None,
                 on_divergence: Optional[Callable[[dict], None]] = None,
                 confirm: Optional[Dict[str, int]] = None):
        self._peers: Dict[str, _AuditPeer] = {}
        self.record_path = record_path
        self.on_divergence = on_divergence
        self.confirm = dict(CONFIRM_STREAK)
        if confirm:
            self.confirm.update(confirm)
        self.beacons = 0
        self.joins = 0
        # (peer_a, peer_b, kind) -> last joined seq (join-count dedup)
        self._join_marks: Dict[tuple, int] = {}
        self._streaks: Dict[tuple, tuple] = {}
        self._confirmed_keys: set = set()
        self.divergences: List[dict] = []

    # -- ingest -----------------------------------------------------------
    def ingest(self, payload: dict, now_ms: Optional[int] = None) -> bool:
        if not isinstance(payload, dict) \
                or payload.get("type") != "audit_beacon":
            return False
        try:
            entries = decode_audit_b64(payload.get("data") or "")
        except AuditCodecError:
            return False
        peer = str(payload.get("peer_id") or payload.get("proc") or "?")
        st = self._peers.get(peer)
        if st is None:
            st = self._peers[peer] = _AuditPeer()
        st.proc = str(payload.get("proc") or "?")
        st.ns = str(payload.get("ns") or "")
        st.last_ms = (time.time_ns() // 1_000_000
                      if now_ms is None else now_ms)
        iv = payload.get("interval_s")
        if isinstance(iv, (int, float)) and iv > 0:
            st.interval_s = float(iv)
        st.beacons += 1
        if isinstance(payload.get("dynamic_world"), bool):
            st.dynamic = payload["dynamic_world"]
        if isinstance(payload.get("buckets"), dict):
            st.buckets = payload["buckets"]
        for e in entries:
            ring = st.rings.setdefault(e.section, {})
            if ring and e.seq not in ring \
                    and max(ring) - e.seq > RING_KEEP:
                # seq regressed far past the re-ship window: the peer's
                # chain restarted (e.g. a new manager run) — old-run
                # entries must never join against new-run watermarks
                ring.clear()
                st.stable[e.section] = 0
            ring[e.seq] = e
            while len(ring) > RING_KEEP:
                ring.pop(next(iter(ring)))
            prev = st.latest.get(e.section)
            if prev is not None and prev.digest == e.digest \
                    and prev.count == e.count:
                st.stable[e.section] = st.stable.get(e.section, 0) + 1
            else:
                st.stable[e.section] = 0
            st.latest[e.section] = e
            st.epoch = max(st.epoch, e.epoch)
        self.beacons += 1
        return True

    # -- evaluation -------------------------------------------------------
    def _record(self, rec: dict) -> None:
        self.divergences.append(rec)
        del self.divergences[:-256]
        # callback BEFORE the jsonl write: on_divergence may enrich the
        # record (the standalone auditor attaches an automatic capture1
        # pointer, ISSUE 11) and the persisted line must carry it
        if self.on_divergence is not None:
            try:
                self.on_divergence(rec)
            except Exception:
                pass  # a side-channel must never lose the record itself
        if self.record_path:
            try:
                with open(self.record_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass

    def _fresh(self, st: _AuditPeer, now_ms: int) -> bool:
        """A peer still beaconing inside its silent threshold.  Only
        fresh peers participate in digest joins — a dead peer (e.g. a
        replaced manager whose random peer_id retired with it) must
        surface as `silent`, never lend its stale rings to a join."""
        return now_ms - st.last_ms <= 3000 * st.interval_s + 1000

    def _count_join(self, a: str, b: str, kind: str, seq: int) -> None:
        """Count a join only the first time this (pair, seq) watermark
        is compared — evaluate() may re-walk the same rings many times
        between beacons, and the join count must measure data, not
        polling cadence."""
        if self._join_marks.get((a, b, kind)) != seq:
            self._join_marks[(a, b, kind)] = seq
            self.joins += 1

    def _current(self, now_ms: int) -> List[dict]:
        """Raw (unconfirmed) divergences visible right now."""
        out = []
        by_ns: Dict[str, List[Tuple[str, _AuditPeer]]] = {}
        for name, st in self._peers.items():
            if self._fresh(st, now_ms):
                by_ns.setdefault(st.ns, []).append((name, st))
        for ns, peers in by_ns.items():
            mgrs = [(n, s) for n, s in peers if SEC_SHADOW in s.rings]
            sols = [(n, s) for n, s in peers if SEC_MIRROR in s.rings]
            for mn, ms in mgrs:
                for sn, ss in sols:
                    common = (set(ms.rings[SEC_SHADOW])
                              & set(ss.rings[SEC_MIRROR]))
                    if not common:
                        continue
                    seq = max(common)
                    a = ms.rings[SEC_SHADOW][seq]
                    b = ss.rings[SEC_MIRROR][seq]
                    self._count_join(mn, sn, "roster", seq)
                    if (a.digest, a.count) != (b.digest, b.count):
                        out.append({"class": "roster", "ns": ns,
                                    "peer_a": mn, "peer_b": sn,
                                    "seq": seq, "epoch": b.epoch,
                                    "_ev": (ms.beacons, ss.beacons),
                                    "detail": f"shadow {digest_hex(a.digest)}"
                                              f"/{a.count} != mirror "
                                              f"{digest_hex(b.digest)}"
                                              f"/{b.count}"})
            for sn, ss in sols:
                dev = ss.rings.get(SEC_DEVICE) or {}
                common = set(ss.rings[SEC_MIRROR]) & set(dev)
                if common:
                    seq = max(common)
                    a = ss.rings[SEC_MIRROR][seq]
                    b = dev[seq]
                    self._count_join(sn, sn, "device", seq)
                    if (a.digest, a.count) != (b.digest, b.count):
                        out.append({"class": "device_mirror", "ns": ns,
                                    "peer_a": sn, "peer_b": sn,
                                    "seq": seq, "epoch": b.epoch,
                                    "_ev": (ss.beacons,),
                                    "detail": "device slab != host mirror"})
            # view: manager in-flight set vs every agent-side view —
            # judged only when both digests held still across beacons
            # (stable), so live churn never reads as divergence
            for mn, ms in mgrs or [(n, s) for n, s in peers
                                   if SEC_LEDGER in s.rings]:
                mv = ms.latest.get(SEC_VIEW)
                if mv is None or ms.stable.get(SEC_VIEW, 0) < 1:
                    continue
                for pn, psn in peers:
                    if pn == mn or SEC_SHADOW in psn.rings \
                            or SEC_LEDGER in psn.rings:
                        continue
                    pv = psn.latest.get(SEC_VIEW)
                    if pv is None or psn.stable.get(SEC_VIEW, 0) < 1:
                        continue
                    if (mv.digest, mv.count) != (pv.digest, pv.count):
                        out.append({"class": "view", "ns": ns,
                                    "peer_a": mn, "peer_b": pn,
                                    "seq": pv.seq, "epoch": pv.epoch,
                                    "_ev": (ms.beacons, psn.beacons),
                                    "detail": f"manager holds {mv.count} "
                                              f"in-flight, agents hold "
                                              f"{pv.count}"})
            # epoch tracking: every epoch-aware peer in a namespace must
            # converge on the same world epoch; a dynamic-world-OFF peer
            # in an epoch>0 fleet is the PR 9 caveat made visible
            aware = [(n, s) for n, s in peers if s.dynamic is not False
                     and (SEC_SHADOW in s.rings or SEC_MIRROR in s.rings
                          or SEC_LEDGER in s.rings)]
            epochs = {n: s.epoch for n, s in aware}
            if epochs and max(epochs.values()) != min(epochs.values()):
                hi = max(epochs, key=epochs.get)
                lo = min(epochs, key=epochs.get)
                out.append({"class": "stale_epoch", "ns": ns,
                            "peer_a": hi, "peer_b": lo,
                            "seq": 0, "epoch": epochs[hi],
                            "_ev": tuple(s.beacons for _, s in aware),
                            "detail": f"{hi}@{epochs[hi]} vs "
                                      f"{lo}@{epochs[lo]}"})
            off = [(n, s) for n, s in peers if s.dynamic is False]
            fleet_epoch = max((s.epoch for _, s in peers), default=0)
            if off and fleet_epoch > 0:
                out.append({"class": "epoch_unaware", "ns": ns,
                            "peer_a": off[0][0], "peer_b": "",
                            "seq": 0, "epoch": fleet_epoch,
                            "_ev": (off[0][1].beacons,),
                            "detail": f"{off[0][0]} runs dynamic-world "
                                      f"OFF while the fleet is at epoch "
                                      f"{fleet_epoch}"})
        # silent peers: quiet past 3 of their own intervals (plus a 1 s
        # absolute grace — beacons ride each process's idle loop window,
        # so sub-second intervals jitter by whole loop iterations) while
        # some other peer is still fresh (the whole fleet pausing is not
        # a divergence — the harness may simply have stopped)
        fresh = any(now_ms - s.last_ms < 1500 * s.interval_s
                    for s in self._peers.values())
        if fresh:
            for name, st in self._peers.items():
                if now_ms - st.last_ms > 3000 * st.interval_s + 1000:
                    quiet_s = (now_ms - st.last_ms) / 1000.0
                    out.append({"class": "silent", "ns": st.ns,
                                "peer_a": name, "peer_b": "",
                                "seq": 0, "epoch": st.epoch,
                                "detail": f"no audit beacon for "
                                          f"{quiet_s:.1f}s"})
        return out

    def evaluate(self, now_ms: Optional[int] = None) -> List[dict]:
        """One judgment pass: update streaks, confirm divergences that
        survived their class threshold, return the CONFIRMED records
        newly emitted by this call."""
        now_ms = time.time_ns() // 1_000_000 if now_ms is None else now_ms
        current = self._current(now_ms)
        seen_keys = set()
        confirmed = []
        for d in current:
            key = (d["class"], d["ns"], d["peer_a"], d["peer_b"])
            seen_keys.add(key)
            # fuzzy classes carry an evidence mark (the contributing
            # peers' beacon counts): their streak only advances on FRESH
            # beacons — evaluate() may run many times between beacons,
            # and one transient beacon pair must never count as a
            # "sustained" divergence
            mark = d.pop("_ev", None)
            count, prev_mark = self._streaks.get(key, (0, None))
            if mark is None or mark != prev_mark:
                count += 1
            self._streaks[key] = (count, mark)
            if count >= self.confirm.get(d["class"], 2) \
                    and key not in self._confirmed_keys:
                self._confirmed_keys.add(key)
                rec = {"ts_ms": now_ms, **d}
                self._record(rec)
                confirmed.append(rec)
        for key in list(self._streaks):
            if key not in seen_keys:
                # divergence healed: reset so a NEW episode re-confirms
                # (and re-records) instead of staying latched forever
                del self._streaks[key]
                self._confirmed_keys.discard(key)
        return confirmed

    # -- rollup -----------------------------------------------------------
    def active(self) -> List[dict]:
        """Confirmed divergences still diverging right now — one record
        per key (the NEWEST: after a heal -> re-confirm cycle the
        history holds several records for the same key, and the live
        view must show the current episode, not every past one)."""
        newest: Dict[tuple, dict] = {}
        for d in self.divergences:
            key = (d["class"], d["ns"], d["peer_a"], d["peer_b"])
            if key in self._confirmed_keys:
                newest[key] = d  # later records overwrite earlier ones
        return list(newest.values())

    def verdict(self) -> str:
        classes = {d["class"] for d in self.active()}
        if classes & set(RED_CLASSES):
            return "red"
        if classes & set(AMBER_CLASSES):
            return "amber"
        return "green"

    def epochs(self) -> Dict[str, dict]:
        return {name: {"epoch": st.epoch, "dynamic": st.dynamic,
                       "ns": st.ns, "proc": st.proc}
                for name, st in sorted(self._peers.items())}

    def status(self) -> dict:
        classes: Dict[str, int] = {}
        for d in self.divergences:
            classes[d["class"]] = classes.get(d["class"], 0) + 1
        return {
            "verdict": self.verdict(),
            "peers": len(self._peers),
            "beacons": self.beacons,
            "joins": self.joins,
            "divergences": len(self.divergences),
            "active": self.active(),
            "classes": classes,
            "epochs": self.epochs(),
            "last": self.divergences[-1] if self.divergences else None,
        }


# ---------------------------------------------------------------------------
# the bisect driller: range-hash recursion to the first divergent lane
# ---------------------------------------------------------------------------

class AuditDriller:
    """Bus-driven binary search over lane space.  ``transport`` sends one
    drill request and returns the matching response (or None on
    timeout); the default rides a BusClient.  ~2·log2(span) round trips
    localize one corrupted lane without shipping any fleet state."""

    def __init__(self, bus=None, timeout: float = 3.0,
                 leaf: int = DRILL_LEAF,
                 transport: Optional[Callable[[dict], Optional[dict]]]
                 = None):
        self.bus = bus
        self.timeout = timeout
        self.leaf = leaf
        self._req_id = 0
        self.requests = 0
        self._transport = transport or self._bus_transport

    def _bus_transport(self, req: dict) -> Optional[dict]:
        self.bus.publish(AUDIT_TOPIC, req, raw=True)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            f = self.bus.recv(timeout=min(0.25,
                                          deadline - time.monotonic()))
            if not f or f.get("op") != "msg":
                continue
            d = f.get("data") or {}
            if d.get("type") == "audit_drill_response" \
                    and d.get("req_id") == req["req_id"]:
                return d
        return None

    def _ask(self, target: str, view: str, lo: int, hi: int,
             ns: str = "", rows: bool = False) -> Optional[dict]:
        self._req_id += 1
        self.requests += 1
        req = {"type": "audit_drill_request", "req_id": self._req_id,
               "target": target, "view": view, "lo": lo, "hi": hi,
               "ns": ns}
        if rows:
            req["rows"] = True
        return self._transport(req)

    def drill_lanes(self, target_a: str, view_a: str, target_b: str,
                    view_b: str, span: int = 1 << 20,
                    ns: str = "") -> dict:
        """Bisect [0, span) down to the first divergent leaf and diff its
        rows.  Returns ``{"findings": [...], "requests": n}`` — each
        finding names the lane, the peer id, the divergent field and
        both sides' values — or an ``error`` key when a side went
        unresponsive / no divergence was visible."""
        t0 = time.perf_counter()
        req0 = self.requests

        def pair(lo, hi):
            a = self._ask(target_a, view_a, lo, hi, ns)
            b = self._ask(target_b, view_b, lo, hi, ns)
            if a is None or b is None:
                return None
            return a, b

        def differ(a, b):
            return (a.get("digest"), a.get("count")) \
                != (b.get("digest"), b.get("count"))

        top = pair(0, span)
        if top is None:
            return {"error": "no_response", "requests": self.requests - req0}
        if not differ(*top):
            return {"findings": [], "requests": self.requests - req0,
                    "elapsed_s": round(time.perf_counter() - t0, 3)}
        lo, hi = 0, span
        while hi - lo > self.leaf:
            mid = (lo + hi) // 2
            left = pair(lo, mid)
            if left is None:
                return {"error": "no_response",
                        "requests": self.requests - req0}
            if differ(*left):
                hi = mid  # the FIRST divergent half (ISSUE 10 contract)
                continue
            right = pair(mid, hi)
            if right is None:
                return {"error": "no_response",
                        "requests": self.requests - req0}
            if differ(*right):
                lo = mid
                continue
            # transient: state advanced between the parent and child
            # queries and the halves agree again — report honestly
            return {"findings": [], "transient": True,
                    "requests": self.requests - req0}
        leaf = (self._ask(target_a, view_a, lo, hi, ns, rows=True),
                self._ask(target_b, view_b, lo, hi, ns, rows=True))
        if leaf[0] is None or leaf[1] is None:
            return {"error": "no_response", "requests": self.requests - req0}
        rows_a = {r[0]: r for r in leaf[0].get("rows") or []}
        rows_b = {r[0]: r for r in leaf[1].get("rows") or []}
        findings = []
        for lane in sorted(set(rows_a) | set(rows_b)):
            ra, rb = rows_a.get(lane), rows_b.get(lane)
            name = (ra or rb)[4] if (ra or rb) else ""
            if ra is None or rb is None:
                findings.append({"lane": lane, "peer": name,
                                 "field": "active",
                                 "a": None if ra is None else 1,
                                 "b": None if rb is None else 1})
                continue
            if not ra[4] and rb[4]:
                name = rb[4]
            for field, k in (("pos", 1), ("goal", 2)):
                if ra[k] != rb[k]:
                    findings.append({"lane": lane, "peer": name,
                                     "field": field,
                                     "a": ra[k], "b": rb[k]})
        return {"findings": findings, "lo": lo, "hi": hi,
                "requests": self.requests - req0,
                "elapsed_s": round(time.perf_counter() - t0, 3)}


def render_finding(f: dict, width: Optional[int] = None,
                   side_a: str = "manager", side_b: str = "solverd") -> str:
    """Operator string: "agent <id>'s goal differs: manager says (88,12),
    solverd says (88,11)"."""
    def cell(v):
        if v is None:
            return "absent"
        if width:
            return f"({v % width},{v // width})"
        return str(v)

    who = f.get("peer") or f"lane {f.get('lane')}"
    return (f"agent {who}'s {f['field']} differs: {side_a} says "
            f"{cell(f.get('a'))}, {side_b} says {cell(f.get('b'))}")


# ---------------------------------------------------------------------------
# standalone auditor CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse

    from p2p_distributed_tswap_tpu.runtime.bus_client import BusClient

    ap = argparse.ArgumentParser(
        description="fleet state-consistency auditor (mapd.audit)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7400)
    ap.add_argument("--once", action="store_true",
                    help="collect for --wait seconds, judge, exit "
                         "0 green / 1 red or amber / 2 no beacons")
    ap.add_argument("--wait", type=float, default=6.0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--drill", action="store_true",
                    help="on a confirmed roster divergence, bisect to "
                         "the exact lane and print the finding")
    ap.add_argument("--record", default=None, metavar="DIR",
                    help="append confirmed divergences to "
                         "DIR/auditor.audit.jsonl (blackbox --audit "
                         "merges them)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="where the fleet's flight rings dump "
                         "(default: $JG_FLIGHT_DIR, then --record). A "
                         "confirmed RED divergence then also dumps an "
                         "automatic capture1 next to the rings (ISSUE "
                         "11) and the audit record gains a `capture` "
                         "pointer — the incident arrives pre-packaged "
                         "for fleetsim --replay")
    args = ap.parse_args(argv)

    try:
        bus = BusClient(host=args.host, port=args.port, peer_id="auditor",
                        reconnect=not args.once)
    except OSError as e:
        import sys
        print(f"auditor: cannot reach bus at {args.host}:{args.port} "
              f"({e})", file=sys.stderr)
        return 2
    bus.subscribe(AUDIT_TOPIC, raw=True)

    record_path = None
    if args.record:
        os.makedirs(args.record, exist_ok=True)
        record_path = os.path.join(args.record, "auditor.audit.jsonl")

    dump = flight_dump_trigger(bus)
    flight_dir = (args.flight_dir or os.environ.get("JG_FLIGHT_DIR")
                  or args.record)
    cap_state = {"at": 0.0}

    def maybe_capture(rec: dict) -> None:
        """RED episode -> automatic capture dump (ISSUE 11 satellite):
        once the pulled flight rings land, rebuild the window as a
        replayable capture1 next to them; the jsonl record (written
        after this callback) carries the pointer.  Throttled like the
        flight dump — one capture per episode window."""
        if not flight_dir or rec.get("class") not in RED_CLASSES:
            return
        now = time.monotonic()
        if now - cap_state["at"] < 30.0:
            return
        cap_state["at"] = now
        time.sleep(1.2)  # flight_dump responses need a beat to land
        from p2p_distributed_tswap_tpu.obs import capture as _capture
        try:
            doc = _capture.from_flight_dir(flight_dir, source="auto_red")
            path = _capture.save(
                os.path.join(flight_dir, "auditor.capture.json"), doc)
            rec["capture"] = str(path)
            print(f"📼 capture1 dumped to {path} "
                  f"({len(doc['tasks'])} task(s)) — replay with "
                  f"fleetsim --replay", flush=True)
        except (_capture.CaptureError, OSError) as e:
            print(f"📼 capture dump skipped: {e}", flush=True)

    def on_div(rec: dict) -> None:
        # sustained divergence: pull the fleet's black boxes (throttled)
        # so the moments before the fork survive
        dump(rec)
        maybe_capture(rec)
        print(f"🔴 AUDIT divergence [{rec['class']}] "
              f"{rec.get('peer_a')}↔{rec.get('peer_b')} "
              f"seq={rec.get('seq')} epoch={rec.get('epoch')}: "
              f"{rec.get('detail')}", flush=True)

    joiner = AuditJoiner(record_path=record_path, on_divergence=on_div)

    def pump(seconds: float) -> None:
        end = time.monotonic() + seconds
        last_eval = 0.0
        while True:
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            f = bus.recv(timeout=min(0.5, remaining))
            if f and f.get("op") == "msg":
                joiner.ingest(f.get("data") or {})
            if time.monotonic() - last_eval >= 1.0:
                last_eval = time.monotonic()
                joiner.evaluate()

    def maybe_drill() -> None:
        if not args.drill:
            return
        for d in joiner.active():
            if d["class"] != "roster":
                continue
            driller = AuditDriller(bus=bus)
            res = driller.drill_lanes(d["peer_a"], "shadow",
                                      d["peer_b"], "mirror",
                                      ns=d.get("ns") or "")
            for f in res.get("findings") or []:
                print("🔎 " + render_finding(f), flush=True)
            if res.get("error"):
                print(f"🔎 drill failed: {res['error']}", flush=True)

    if args.once:
        pump(args.wait)
        joiner.evaluate()
        maybe_drill()
        st = joiner.status()
        if args.json:
            print(json.dumps(st, indent=2))
        else:
            print(f"AUDIT {st['verdict'].upper()}: {st['peers']} peer(s), "
                  f"{st['joins']} join(s), {st['divergences']} "
                  f"divergence(s)")
            for d in st["active"]:
                print(f"  [{d['class']}] {d['peer_a']}↔{d['peer_b']}: "
                      f"{d['detail']}")
        if st["beacons"] == 0:
            return 2
        return 0 if st["verdict"] == "green" else 1

    try:
        while True:
            pump(2.0)
            st = joiner.status()
            print(f"AUDIT {st['verdict'].upper()} peers={st['peers']} "
                  f"joins={st['joins']} div={st['divergences']} "
                  f"epochs=" + ",".join(
                      f"{p}:{e['epoch']}" for p, e in st["epochs"].items()),
                  flush=True)
            maybe_drill()
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
