"""Declarative fleet SLOs evaluated against live observability signals.

The stack up to PR 6 *produces* rich live signals — registry beacons
merged into the fleet rollup (obs/fleet_aggregator.py) and causal task
timelines with phase attribution (analysis/task_timeline.py) — but
nothing *judges* them.  This module closes that loop: a small
declarative spec (dict / JSON file) names the service-level objectives a
fleet must meet, and the engine evaluates each one against a flat
``signals`` mapping, producing a machine-readable verdict per SLO:

- ``pass`` / ``fail`` — the observed value met / breached the threshold;
- ``unknown`` — the signal is ABSENT from the inputs.  Missing telemetry
  is never a silent pass: an SLO whose signal went dark is exactly the
  regression the gate exists to catch, so ``unknown`` fails a strict
  gate (exit 2, distinct from a threshold breach's exit 1).

Spec format (JSON or dict)::

    {"name": "rated-load",
     "slos": [
       {"name": "p99_dispatch_claim_wire_ms",
        "signal": "timeline.phase_p99_ms.wire", "max": 500.0,
        "phases": "timeline.fleet_phases_p99_ms"},
       {"name": "completion_ratio",
        "signal": "fleet.completion_ratio", "min": 0.99},
       {"name": "tasks_per_s", "signal": "fleet.tasks_per_s", "min": 2.0},
       {"name": "slow_consumer_evictions",
        "signal": "bus.slow_consumer_evictions", "max": 0}]}

Each SLO entry:

- ``signal``: dotted path into the signals mapping (nested dicts);
- ``min`` and/or ``max``: inclusive bounds — at least one is required
  (``observed < min`` or ``observed > max`` breaches);
- ``phases`` (optional, latency SLOs): dotted path to a ``{phase: ms}``
  mapping; the verdict then carries ``breaching_phase`` — the phase with
  the largest attributed latency — so a breached latency SLO names
  WHERE the time went (queueing vs wire vs planning vs travel), not
  just that it went somewhere.

Signals come from two sources, flattened by the helpers below:

- :func:`signals_from_rollup` — the fleet aggregator rollup (tasks/s,
  completion ratio, bus health, per-manager tick percentiles);
- :func:`signals_from_timeline` — a task_timeline summary (per-phase
  p50/p95/p99, end-to-end percentiles, coverage).

``analysis/fleetsim.py`` is the primary producer; ``analysis/
fleet_top.py`` renders live verdicts from the same engine; the CLI
(``python -m p2p_distributed_tswap_tpu.obs.slo --signals f --spec g``)
re-judges a saved signals dump against any spec — the CI gate uses this
to prove the gate trips on a known-breaching spec without a second
fleet bring-up.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Union

# The default spec: the rated-load objectives named by ROADMAP item 4.
# One planning tick (500 ms) bounds the p99 dispatch->claim wire phase;
# the bus must shed nothing at rated load; a task dispatched is a task
# completed (99%: the in-flight tail of a live window is real, a
# completion COLLAPSE is what the floor catches).
DEFAULT_SPEC: dict = {
    "name": "rated-load",
    "slos": [
        {"name": "p99_dispatch_claim_wire_ms",
         "signal": "timeline.phase_p99_ms.wire", "max": 500.0,
         "phases": "timeline.fleet_phases_p99_ms"},
        {"name": "completion_ratio",
         "signal": "fleet.completion_ratio", "min": 0.99},
        {"name": "slow_consumer_evictions",
         "signal": "bus.slow_consumer_evictions", "max": 0},
        {"name": "tasks_per_s", "signal": "fleet.tasks_per_s", "min": 0.5},
    ],
}

_STATUS_ORDER = {"pass": 0, "unknown": 1, "fail": 2}


class SpecError(ValueError):
    """Malformed SLO spec (bad shape, missing bounds, dup names)."""


def load_spec(source: Union[dict, str, None]) -> dict:
    """Normalize + validate a spec from a dict, a JSON file path, a JSON
    string, or None (the default spec).  Raises :class:`SpecError` on a
    malformed spec — a gate must never run against garbage silently."""
    if source is None:
        spec = json.loads(json.dumps(DEFAULT_SPEC))  # deep copy
    elif isinstance(source, dict):
        spec = source
    elif isinstance(source, str):
        text = source
        if not source.lstrip().startswith("{"):
            with open(source) as f:
                text = f.read()
        try:
            spec = json.loads(text)
        except json.JSONDecodeError as e:
            raise SpecError(f"spec is not valid JSON: {e}") from None
    else:
        raise SpecError(f"unsupported spec source {type(source).__name__}")
    if not isinstance(spec, dict) or not isinstance(spec.get("slos"), list) \
            or not spec["slos"]:
        raise SpecError('spec must be {"name": ..., "slos": [non-empty]}')
    seen = set()
    for i, slo in enumerate(spec["slos"]):
        if not isinstance(slo, dict):
            raise SpecError(f"slos[{i}] is not an object")
        name = slo.get("name") or slo.get("signal")
        if not name:
            raise SpecError(f"slos[{i}] has neither name nor signal")
        slo["name"] = str(name)
        if slo["name"] in seen:
            raise SpecError(f"duplicate SLO name {slo['name']!r}")
        seen.add(slo["name"])
        if not isinstance(slo.get("signal"), str):
            raise SpecError(f"slos[{i}] ({slo['name']}): missing signal path")
        lo, hi = slo.get("min"), slo.get("max")
        if lo is None and hi is None:
            raise SpecError(
                f"slos[{i}] ({slo['name']}): needs min and/or max")
        for bound, v in (("min", lo), ("max", hi)):
            if v is not None and not isinstance(v, (int, float)):
                raise SpecError(
                    f"slos[{i}] ({slo['name']}): {bound} must be a number")
        if lo is not None and hi is not None and lo > hi:
            raise SpecError(
                f"slos[{i}] ({slo['name']}): min {lo} > max {hi}")
    spec.setdefault("name", "unnamed")
    return spec


def lookup(signals: dict, path: str):
    """Resolve a dotted path through nested dicts; None when any segment
    is absent.  A LITERAL dotted key wins over nesting at each level
    (signal producers use flat dotted names like ``bus.slow_consumer_
    evictions``)."""
    node = signals
    while path:
        if not isinstance(node, dict):
            return None
        if path in node:
            return node[path]
        head, dot, rest = path.partition(".")
        # longest-literal-prefix match: "timeline.phase_p99_ms.wire" may
        # be stored as {"timeline": {"phase_p99_ms": {"wire": v}}} or as
        # {"timeline.phase_p99_ms": {"wire": v}}
        match = None
        probe = head
        remainder = rest
        while True:
            if probe in node:
                match = (probe, remainder)
            if not remainder:
                break
            nxt, _, remainder2 = remainder.partition(".")
            probe = probe + "." + nxt
            remainder = remainder2
        if match is None:
            return None
        node = node[match[0]]
        path = match[1]
    return node


def _breaching_phase(signals: dict, phases_path: str) -> Optional[str]:
    """The phase carrying the largest attributed latency — the answer to
    'WHERE did the breached latency budget go'."""
    phases = lookup(signals, phases_path)
    if not isinstance(phases, dict) or not phases:
        return None
    best, best_v = None, None
    for name, v in phases.items():
        if isinstance(v, dict):  # {p50,p95,p99} shape: judge by p99
            v = v.get("p99")
        if not isinstance(v, (int, float)):
            continue
        if best_v is None or v > best_v:
            best, best_v = name, v
    return best


def evaluate_one(slo: dict, signals: dict) -> dict:
    """Judge ONE normalized SLO entry against ``signals`` — the
    single-evaluation core shared by the offline gate (:func:`evaluate`)
    and the continuous watcher (obs/health.py), so a live verdict and a
    re-judged saved dump can never drift apart.  A missing or
    non-numeric signal is an explicit ``unknown`` status — never a
    silent pass — in BOTH paths."""
    observed = lookup(signals, slo["signal"])
    threshold = {k: slo[k] for k in ("min", "max") if slo.get(k)
                 is not None}
    v = {"name": slo["name"], "signal": slo["signal"],
         "observed": observed, "threshold": threshold}
    if not isinstance(observed, (int, float)) \
            or isinstance(observed, bool):
        v["observed"] = None if not isinstance(
            observed, (int, float, str)) else observed
        v["status"] = "unknown"
    else:
        breached = ((slo.get("min") is not None
                     and observed < slo["min"])
                    or (slo.get("max") is not None
                        and observed > slo["max"]))
        v["status"] = "fail" if breached else "pass"
    if slo.get("phases"):
        # attribution rides the verdict pass OR fail — a passing
        # latency SLO's dominant phase is the headroom map
        bp = _breaching_phase(signals, slo["phases"])
        if bp is not None:
            v["breaching_phase"] = bp
    return v


def evaluate(spec: Union[dict, str, None], signals: dict) -> dict:
    """Judge every SLO in ``spec`` against ``signals``.

    Returns ``{"spec": name, "ok": bool, "failed": [...], "unknown":
    [...], "verdicts": [{name, signal, observed, threshold, status,
    breaching_phase?}]}`` with verdicts in spec order.  ``ok`` is True
    only when EVERY SLO passed — unknown is not a pass."""
    spec = load_spec(spec)
    verdicts: List[dict] = [evaluate_one(slo, signals)
                            for slo in spec["slos"]]
    failed = [v["name"] for v in verdicts if v["status"] == "fail"]
    unknown = [v["name"] for v in verdicts if v["status"] == "unknown"]
    return {"spec": spec.get("name", "unnamed"),
            "ok": not failed and not unknown,
            "failed": failed, "unknown": unknown,
            "verdicts": verdicts}


def exit_code(result: dict) -> int:
    """Gate exit status: 0 all pass, 1 any threshold breach, 2 no breach
    but missing signals (telemetry went dark — still not a pass)."""
    if result["failed"]:
        return 1
    if result["unknown"]:
        return 2
    return 0


# -- signal extraction ------------------------------------------------------

def signals_from_rollup(rollup: dict) -> dict:
    """Flatten a fleet_aggregator rollup into SLO-addressable signals."""
    out: Dict[str, object] = {}
    fleet = rollup.get("fleet") or {}
    for k in ("tasks_per_s", "completion_ratio", "tasks_dispatched",
              "tasks_completed", "tasks_pending", "peers", "stale_peers",
              "counter_resets", "ticks", "ticks_over_budget"):
        if fleet.get(k) is not None:
            out[f"fleet.{k}"] = fleet[k]
    evictions = drops = 0
    saw_bus = False
    for p in (rollup.get("peers") or {}).values():
        bus = p.get("bus")
        if bus:
            saw_bus = True
            evictions += bus.get("slow_consumer_evictions") or 0
            drops += bus.get("slow_consumer_drops") or 0
        if p.get("proc", "").startswith("manager"):
            # WORST manager wins each latency signal: a multi-manager
            # fleet must not let the healthiest (or lexicographically
            # last) peer mask a sick one
            def _worst(key, value):
                if value is None:
                    return
                prev = out.get(key)
                if prev is None or value > prev:
                    out[key] = value
            if p.get("tick"):
                _worst("manager.tick_p50_ms", p["tick"].get("p50_ms"))
                _worst("manager.tick_p95_ms", p["tick"].get("p95_ms"))
            if p.get("tasks"):
                _worst("manager.task_latency_p95_ms",
                       p["tasks"].get("latency_p95_ms"))
    if saw_bus:
        # only when a busd beacon was actually seen: zero-by-absence
        # would let "no bus telemetry" pass a zero-evictions SLO
        out["bus.slow_consumer_evictions"] = evictions
        out["bus.slow_consumer_drops"] = drops
    return out


def signals_from_timeline(summary: dict) -> dict:
    """Flatten a task_timeline summary (phase attribution percentiles)."""
    out: Dict[str, object] = {}
    phases = summary.get("fleet_phases_ms") or {}
    p99_map: Dict[str, float] = {}
    for phase, pcts in phases.items():
        for q in ("p50", "p95", "p99"):
            if pcts.get(q) is not None:
                out[f"timeline.phase_{q}_ms.{phase}"] = pcts[q]
        if pcts.get("p99") is not None:
            p99_map[phase] = pcts["p99"]
    if p99_map:
        out["timeline.fleet_phases_p99_ms"] = p99_map
    e2e = summary.get("end_to_end_ms") or {}
    for q in ("p50", "p95", "p99"):
        if e2e.get(q) is not None:
            out[f"timeline.end_to_end_{q}_ms"] = e2e[q]
    for k in ("coverage", "tasks_complete", "tasks_acked", "orphans",
              "hop_violations"):
        if summary.get(k) is not None:
            out[f"timeline.{k}"] = summary[k]
    return out


# -- rendering --------------------------------------------------------------

_MARK = {"pass": "✓", "fail": "✗", "unknown": "?"}
_COLOR = {"pass": "\x1b[32m", "fail": "\x1b[31m", "unknown": "\x1b[33m"}


def _fmt_threshold(t: dict) -> str:
    parts = []
    if "min" in t:
        parts.append(f">= {t['min']:g}")
    if "max" in t:
        parts.append(f"<= {t['max']:g}")
    return " and ".join(parts) or "-"


def _fmt_observed(v) -> str:
    if v is None:
        return "missing"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def render_line(result: dict, color: bool = False) -> str:
    """One status line per SLO, joined — the fleet_top live view shape."""
    parts = []
    for v in result["verdicts"]:
        mark = _MARK[v["status"]]
        body = (f"{mark} {v['name']} {_fmt_observed(v['observed'])} "
                f"({_fmt_threshold(v['threshold'])})")
        if v["status"] == "fail" and v.get("breaching_phase"):
            body += f" [{v['breaching_phase']}]"
        if color:
            body = f"{_COLOR[v['status']]}{body}\x1b[0m"
        parts.append(body)
    head = "SLO[{}] ".format(result["spec"])
    return head + " | ".join(parts)


def render_md(result: dict) -> str:
    """Markdown verdict table (the .md half of the committed artifact)."""
    lines = [f"## SLO verdict — spec `{result['spec']}` — "
             + ("**PASS**" if result["ok"] else
                ("**FAIL**" if result["failed"] else "**UNKNOWN**")),
             "",
             "| SLO | signal | observed | threshold | status "
             "| breaching phase |",
             "|---|---|---|---|---|---|"]
    for v in result["verdicts"]:
        # the phase column names a BREACHING phase: attribution is only
        # rendered on a failed SLO (passing verdicts keep the dominant
        # phase in the JSON for headroom reading, but not here)
        phase = v.get("breaching_phase", "-") if v["status"] == "fail" \
            else "-"
        lines.append(
            f"| {v['name']} | `{v['signal']}` "
            f"| {_fmt_observed(v['observed'])} "
            f"| {_fmt_threshold(v['threshold'])} "
            f"| {v['status'].upper()} "
            f"| {phase} |")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    """Re-judge a saved signals dump against a spec (the CI breach
    drill): ``python -m p2p_distributed_tswap_tpu.obs.slo --signals
    out.json [--spec spec.json]``.  ``--signals`` accepts either a raw
    signals dict or a fleetsim verdict artifact (its ``signals`` key)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--signals", required=True,
                    help="JSON file: a signals dict, or an artifact "
                         "with a 'signals' key")
    ap.add_argument("--spec", default=None,
                    help="SLO spec JSON file (default: built-in "
                         "rated-load spec)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    with open(args.signals) as f:
        payload = json.load(f)
    signals = payload
    if isinstance(payload, dict):
        if isinstance(payload.get("signals"), dict):
            signals = payload["signals"]
        elif payload.get("rungs"):  # a fleetsim artifact: newest rung
            signals = payload["rungs"][-1].get("signals") or {}
    result = evaluate(args.spec, signals)
    print(json.dumps(result, indent=2) if args.as_json
          else render_line(result))
    return exit_code(result)


if __name__ == "__main__":
    import sys

    sys.exit(main())
