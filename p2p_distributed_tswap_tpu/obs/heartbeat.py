"""Per-tick heartbeat: one JSON line per planning tick to a sidecar file.

The trace ring answers "where did the time go inside a tick"; the heartbeat
answers "is the daemon keeping its 500 ms budget RIGHT NOW" — a line a
human can ``tail -f`` and a harness can parse without replaying a trace.
Schema (all times ms):

    {"tick": N, "seq": S, "ts_ms": unix_ms, "agents": A,
     "ms": {"decode": .., "field_sweep": .., "step_dispatch": ..,
            "device_sync": .., "encode": .., "total": ..},
     "counters": {...tracer counters snapshot...},
     "budget_ms": 500.0, "over_budget": false}

Writers are cheap enough to leave on whenever tracing is on: one dict, one
``json.dumps``, one buffered write per tick.  The file is line-buffered so
``tail -f`` sees ticks as they land.

The LIVE budget accounting (tick_ms histogram, tick.over_budget counter)
lives in the unified registry (obs/registry.py), written by
``TickRunner.handle`` whether or not a heartbeat file is open — this
writer's instance counters only feed the sidecar lines and the stats dump's
``over_budget_ticks`` convenience field.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

# the centralized manager's planning tick (cpp manager --planning-interval-ms
# default, ref manager.rs:567): the budget every heartbeat is judged against
TICK_BUDGET_MS = 500.0


class HeartbeatWriter:
    def __init__(self, path: str, budget_ms: float = TICK_BUDGET_MS):
        self.path = path
        self.budget_ms = budget_ms
        self.ticks = 0
        self.over_budget_ticks = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)  # line-buffered: tail -f

    def beat(self, seq, agents: int, phase_ms: dict,
             counters: Optional[dict] = None) -> dict:
        total = phase_ms.get("total")
        if total is None:
            total = sum(phase_ms.values())
        over = total > self.budget_ms
        self.ticks += 1
        if over:
            self.over_budget_ticks += 1
        line = {"tick": self.ticks, "seq": seq,
                "ts_ms": time.time_ns() // 1_000_000, "agents": agents,
                "ms": {k: round(v, 3) for k, v in phase_ms.items()},
                "counters": counters or {},
                "budget_ms": self.budget_ms, "over_budget": over}
        self._f.write(json.dumps(line) + "\n")
        return line

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
