"""Structured span tracer: the runtime-observability core (obs/).

The metrics subsystem (metrics/task_metrics.py) is offline CSV; this module
is the LIVE side — monotonic-clock spans with nesting, a thread-safe ring
buffer, and a counters/gauges registry — exported as Chrome trace-event
JSONL (one event object per line; ``catapult``/Perfetto open a JSON array,
so :func:`analysis/trace_report.py --perfetto` wraps the lines, and the
lines themselves are what the merge tooling consumes).

Design constraints, in priority order:

1. **Near-zero cost when off.**  Tracing is gated by ``JG_TRACE=1`` (or an
   explicit :func:`configure` call).  Disabled, :func:`span` returns one
   shared no-op context manager and :func:`count`/:func:`gauge` return
   after a single attribute check — no allocation, no locking, no clock
   read.  Nothing in the jitted device programs is touched either way: all
   spans live on the HOST side of the dispatch boundary, where a
   ``perf_counter_ns`` pair per phase is noise against a ~100 ms tick.
2. **Mergeable across processes.**  Event timestamps are wall-clock-anchored
   microseconds: each tracer records ``(time_ns, perf_counter_ns)`` once at
   creation and emits ``anchor + (mono - mono0)``.  Durations stay purely
   monotonic; only the anchor is wall time, so host-runtime (C++,
   cpp/common/trace.hpp — same schema) and solver traces interleave on one
   Perfetto timeline with ~ms cross-process alignment.
3. **Bounded memory.**  The ring buffer keeps the newest ``capacity``
   events (default 64k ≈ a few MB); long-running daemons flush
   periodically (solverd flushes on heartbeat cadence) so nothing is lost
   in practice, and an unflushed crash still leaves the newest window.

Span nesting is tracked per thread (a thread-local stack); every event
carries its parent span name in ``args.parent`` so the report tool can
attribute child phases to their tick without relying on timestamp
containment alone.

Counters and gauges are NOT stored here anymore: :func:`count` and
:func:`gauge` delegate to the unified live-metrics registry
(obs/registry.py) unconditionally — they are live telemetry (beacons,
/metrics, stats dumps) and cost one dict op whether or not tracing is on.
Only the *span/event* side stays gated by ``JG_TRACE``; when tracing is
enabled, the registry's counters additionally ride the trace file as
Chrome counter ("C") events on every flush, exactly as before.

Environment:
  JG_TRACE=1        enable tracing
  JG_TRACE_DIR=DIR  where trace/heartbeat files land (default results/trace)
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Iterator, Optional

from p2p_distributed_tswap_tpu.obs import registry as _registry

DEFAULT_CAPACITY = 65536
DEFAULT_DIR = "results/trace"


def _env_enabled() -> bool:
    return os.environ.get("JG_TRACE", "") not in ("", "0")


def trace_dir() -> str:
    return os.environ.get("JG_TRACE_DIR", DEFAULT_DIR)


class _NullSpan:
    """Shared no-op context manager: the entire cost of a disabled span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _LiveSpan:
    """One open span; appends a Chrome complete ("X") event on exit."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_parent")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        stack = self._tracer._stack()
        self._parent = stack[-1] if stack else None
        stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_ns = time.perf_counter_ns() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        self._tracer._emit(self.name, self._t0, dur_ns, self._parent,
                           self.args)
        return False


class Tracer:
    """Thread-safe span/counter registry with a bounded event ring."""

    def __init__(self, proc: str = "py", enabled: Optional[bool] = None,
                 capacity: int = DEFAULT_CAPACITY):
        self.proc = proc
        self.pid = os.getpid()
        self.enabled = _env_enabled() if enabled is None else enabled
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        # counters/gauges live in the unified registry (obs/registry.py)
        self.registry = _registry.get_registry()
        # wall-clock anchor: ts_us = anchor + monotonic delta (see module doc)
        self._mono0 = time.perf_counter_ns()
        self._anchor_us = time.time_ns() // 1000
        self._meta_written: set = set()  # paths this INSTANCE wrote meta to

    # -- span / event emission -------------------------------------------
    def _stack(self) -> list:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = self._local.stack = []
        return s

    def _ts_us(self, mono_ns: int) -> int:
        return self._anchor_us + (mono_ns - self._mono0) // 1000

    def span(self, name: str, **args):
        if not self.enabled:
            return _NULL_SPAN
        return _LiveSpan(self, name, args or None)

    def _emit(self, name: str, t0_ns: int, dur_ns: int,
              parent: Optional[str], args: Optional[dict]) -> None:
        ev = {"name": name, "ph": "X", "ts": self._ts_us(t0_ns),
              "dur": max(0, dur_ns // 1000), "pid": self.pid,
              "tid": threading.get_ident() % (1 << 31),
              "args": dict(args) if args else {}}
        if parent:
            ev["args"]["parent"] = parent
        with self._lock:
            self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        """Point event (process lifecycle, faults): Chrome "i" phase."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "p",
              "ts": self._ts_us(time.perf_counter_ns()), "pid": self.pid,
              "tid": threading.get_ident() % (1 << 31), "args": args}
        with self._lock:
            self._events.append(ev)

    def flow(self, name: str, flow_id: int, phase: str = "t",
             **args) -> None:
        """Chrome flow event ("s" start / "t" step / "f" end): events
        sharing (cat, name, id) are linked into arrows across processes on
        the merged Perfetto timeline — obs/events.py emits one per
        sampled lifecycle hop so a task's journey renders as a chain
        (ISSUE 5).  The id is masked to 63 bits: Chrome ids are unsigned."""
        if not self.enabled or phase not in ("s", "t", "f"):
            return
        ev = {"name": name, "ph": phase, "cat": "task",
              "id": int(flow_id) & ((1 << 63) - 1),
              "ts": self._ts_us(time.perf_counter_ns()), "pid": self.pid,
              "tid": threading.get_ident() % (1 << 31), "args": args}
        if phase in ("t", "f"):
            ev["bp"] = "e"  # bind to the enclosing slice when one exists
        with self._lock:
            self._events.append(ev)

    # -- counters / gauges (live metrics: ALWAYS on, see module doc) ------
    def count(self, name: str, n: int = 1) -> None:
        self.registry.count(name, n)

    def gauge(self, name: str, value: float) -> None:
        self.registry.gauge(name, value)

    def snapshot(self) -> dict:
        """Machine-readable point-in-time state (stats dumps, heartbeats)."""
        with self._lock:
            return {"proc": self.proc, "pid": self.pid,
                    "ts_ms": time.time_ns() // 1_000_000,
                    "counters": self.registry.counters_flat(),
                    "gauges": self.registry.gauges_flat(),
                    "buffered_events": len(self._events)}

    # -- export -----------------------------------------------------------
    def _drain(self) -> list:
        with self._lock:
            evs = list(self._events)
            self._events.clear()
            # registry counters ride along as Chrome counter ("C") events so
            # the merged timeline carries them without a side channel
            ts = self._ts_us(time.perf_counter_ns())
            for cname, v in self.registry.counters_flat().items():
                evs.append({"name": cname, "ph": "C", "ts": ts,
                            "pid": self.pid,
                            "args": {"value": int(v) if float(v).is_integer()
                                     else v}})
        return evs

    def jsonl_lines(self) -> Iterator[str]:
        meta = {"name": "process_name", "ph": "M", "pid": self.pid,
                "args": {"name": self.proc}}
        yield json.dumps(meta)
        for ev in self._drain():
            yield json.dumps(ev)

    def default_path(self, kind: str = "trace") -> str:
        return os.path.join(trace_dir(), f"{self.proc}-{self.pid}.{kind}.jsonl")

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        """Append buffered events (+ a metadata line on first write) as
        JSONL; returns the path written, or None when disabled."""
        if not self.enabled:
            return None
        path = path or self.default_path()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # The process_name meta line is written once per TRACER INSTANCE per
        # path — not "once per file": a re-run appending to an existing file
        # (new pid) still needs its own meta line or the report tool cannot
        # attribute the new events to a process.
        first = path not in self._meta_written
        self._meta_written.add(path)
        with open(path, "a") as f:
            for line in self.jsonl_lines() if first else map(
                    json.dumps, self._drain()):
                f.write(line + "\n")
        return path


# -- module-level singleton (the one most call sites use) -----------------

_tracer = Tracer()
_config_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _tracer


def configure(enabled: Optional[bool] = None, proc: Optional[str] = None,
              capacity: int = DEFAULT_CAPACITY) -> Tracer:
    """(Re)build the global tracer — call once at process entry (daemons
    pass their role name so flush files are self-identifying) or from tests.
    Passing ``enabled=None`` re-reads JG_TRACE.  The process registry is
    cleared too: configure marks a fresh observation epoch (process entry,
    or test isolation)."""
    global _tracer
    with _config_lock:
        _tracer = Tracer(proc=proc or _tracer.proc, enabled=enabled,
                         capacity=capacity)
        _registry.get_registry().clear()
    return _tracer


def enabled() -> bool:
    return _tracer.enabled


def span(name: str, **args):
    return _tracer.span(name, **args)


def complete(name: str, t0_ns: int, dur_ns: int, **args) -> None:
    """Emit a span retroactively from explicit perf_counter_ns timestamps.

    The pipelined solverd tick dispatches request k, then decodes k+1 and
    encodes k-1 while the device runs — its phases no longer nest inside a
    live ``with span(...)`` block, so the tick span is stamped after the
    fact (children attribute via an explicit ``parent`` arg instead of the
    span stack)."""
    if not _tracer.enabled:
        return
    _tracer._emit(name, t0_ns, dur_ns, None, args or None)


def instant(name: str, **args) -> None:
    _tracer.instant(name, **args)


def flow(name: str, flow_id: int, phase: str = "t", **args) -> None:
    _tracer.flow(name, flow_id, phase, **args)


def count(name: str, n: int = 1) -> None:
    _tracer.count(name, n)


def gauge(name: str, value: float) -> None:
    _tracer.gauge(name, value)


def snapshot() -> dict:
    return _tracer.snapshot()


def flush(path: Optional[str] = None) -> Optional[str]:
    return _tracer.flush(path)


class disabled:
    """Context manager that forces tracing OFF inside the block — used by
    bench.py to measure the trace-on vs trace-off step-time delta."""

    def __enter__(self):
        self._was = _tracer.enabled
        _tracer.enabled = False
        return self

    def __exit__(self, *exc):
        _tracer.enabled = self._was
        return False
