"""Periodic metrics beacon: every process publishes its registry snapshot.

Fleet-wide live telemetry rides the bus the fleet already has: each role
(solverd, the C++ managers and agents, busd itself) publishes a compact
:meth:`obs.registry.Registry.snapshot` on topic ``mapd.metrics`` every
~2 s.  The manager-side aggregator (obs/fleet_aggregator.py) and the
``analysis/fleet_top.py`` operator view subscribe and merge the beacons
into a fleet rollup; a peer whose beacons stop arriving surfaces as STALE
(complementing runtime/fleet.py's exit-code capture — a wedged-but-alive
process never exits, but its beacon goes quiet).

Beacon payload schema (topic ``mapd.metrics``):

    {"type": "metrics_beacon", "peer_id": s, "proc": s, "pid": n,
     "ts_ms": unix_ms, "interval_s": 2.0,
     "metrics": {"uptime_s": .., "counters": {...}, "gauges": {...},
                 "hists": {key: {"buckets": [...], "counts": [...],
                                 "sum": .., "count": ..}}}}

The C++ mirror (cpp/common/bus.hpp ``enable_metrics_beacon``) publishes the
exact same schema, so the aggregator is implementation-blind.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from p2p_distributed_tswap_tpu.obs import registry as reg

METRICS_TOPIC = "mapd.metrics"
BEACON_INTERVAL_S = 2.0


class MetricsBeacon:
    """Tick-driven beacon: call :meth:`maybe_beat` from the owning main
    loop (any cadence >= ~1 Hz); it publishes at most once per interval.
    ``bus`` needs only ``publish(topic, data)`` and ``peer_id`` — the real
    BusClient or a test fake both qualify."""

    def __init__(self, bus, proc: str,
                 interval_s: float = BEACON_INTERVAL_S,
                 registry: Optional[reg.Registry] = None):
        self.bus = bus
        self.proc = proc
        self.interval_s = interval_s
        self.registry = registry or reg.get_registry()
        self.published = 0
        self._last = 0.0  # first maybe_beat publishes immediately

    def build_payload(self) -> dict:
        return {
            "type": "metrics_beacon",
            "peer_id": getattr(self.bus, "peer_id", self.proc),
            "proc": self.proc,
            "pid": os.getpid(),
            "ts_ms": time.time_ns() // 1_000_000,
            "interval_s": self.interval_s,
            "metrics": self.registry.snapshot(),
        }

    def maybe_beat(self, now: Optional[float] = None) -> Optional[dict]:
        """Publish a beacon if the interval elapsed; returns the payload
        published, else None."""
        now = time.monotonic() if now is None else now
        if self._last and now - self._last < self.interval_s:
            return None
        self._last = now
        payload = self.build_payload()
        self.bus.publish(METRICS_TOPIC, payload)
        self.published += 1
        return payload
